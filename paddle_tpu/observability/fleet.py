"""Fleet metrics collector: one pane of glass over N host expositions.

Every engine process exposes its own registry (``exporter.py``); a
multi-host gpt13b run or a multi-replica serving fleet therefore has
N scrape targets and no merged view. ``FleetCollector`` closes that
gap with stdlib HTTP only: it *scrapes* member ``/metrics`` +
``/healthz`` endpoints (pull) or *receives* pushed exposition text
(``POST /push``), re-labels every member series with ``host=<name>``,
and serves a merged fleet ``/metrics`` plus a fleet ``/healthz``
rollup.

Merge semantics (per metric family, per label combination):

- **counters** — summed across members (``host="fleet"`` row),
- **gauges**   — min / max / mean across members (``host="fleet"``
  rows carrying a ``stat`` label),
- **histograms** — merged bucket-exactly: the fixed bucket lattice is
  shared by construction (metrics.py), so per-bucket counts, sum,
  count, min and max add/combine without approximation, and merged
  percentiles are IDENTICAL to a single registry fed the union of
  observations (``merged_percentile`` mirrors
  ``Histogram.percentile`` including its min/max clamp — the
  ``_min``/``_max`` exposition rows exist for exactly this).

The ``/healthz`` rollup reports ``degraded`` when any member is
degraded, unreachable, or stale — a member whose reported
``snapshot_age_seconds`` (or, push mode, time since its last push)
exceeds ``stale_after_s`` has a hung or dead engine even if its port
still answers.

Collector self-accounting registers ``paddle_tpu_fleet_*`` metrics
(catalog.fleet_metrics) in its own process registry. All state is
guarded by one lock; scrapes (urlopen) always run OUTSIDE it, so a
slow member can never pin the collector.
"""
from __future__ import annotations

import json
import math
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .metrics import MetricsRegistry, get_registry
from .exporter import CONTENT_TYPE

__all__ = ["FleetCollector", "FleetServer", "parse_exposition",
           "merged_percentile", "DEFAULT_STALE_AFTER_S"]

DEFAULT_STALE_AFTER_S = 30.0

# exposition row suffixes that belong to a histogram family
_HIST_PARTS = ("bucket", "sum", "count", "min", "max")


# ---------------------------------------------------------------------------
# exposition parsing (type-aware: histograms reassembled whole)
# ---------------------------------------------------------------------------
def parse_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse Prometheus text exposition into typed families::

        {name: {"type": "counter"|"gauge",
                "series": {labelkey: float}}}
        {name: {"type": "histogram",
                "series": {labelkey: {"count", "sum", "min", "max",
                                      "buckets": {le_str: count}}}}}

    ``labelkey`` is the sorted ``((k, v), ...)`` tuple
    ``parse_prometheus_text`` uses. Histogram bucket counts come back
    NON-cumulative (de-accumulated in ``le`` order) so families merge
    by plain addition. ``_min``/``_max`` rows (this framework's
    exposition extension) restore the clamp state exact percentile
    merging needs; expositions without them fall back to bucket
    edges."""
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
    from .metrics import parse_prometheus_text

    rows = parse_prometheus_text(text)
    out: Dict[str, Dict[str, Any]] = {}
    hists = {n for n, t in types.items() if t == "histogram"}

    def _hist_of(row_name: str) -> Optional[Tuple[str, str]]:
        for part in _HIST_PARTS:
            suffix = "_" + part
            if row_name.endswith(suffix) and \
                    row_name[:-len(suffix)] in hists:
                return row_name[:-len(suffix)], part
        return None

    cum: Dict[str, Dict[Tuple, List[Tuple[float, str, float]]]] = {}
    for row_name, series in rows.items():
        hp = _hist_of(row_name)
        if hp is None:
            out.setdefault(row_name, {
                "type": types.get(row_name, "gauge"), "series": {}})
            out[row_name]["series"].update(series)
            continue
        base, part = hp
        fam = out.setdefault(base, {"type": "histogram", "series": {}})
        for key, val in series.items():
            if part == "bucket":
                le = dict(key).get("le", "+Inf")
                bare = tuple(kv for kv in key if kv[0] != "le")
                ub = math.inf if le == "+Inf" else float(le)
                cum.setdefault(base, {}).setdefault(bare, []).append(
                    (ub, le, val))
            else:
                s = fam["series"].setdefault(
                    key, {"count": 0, "sum": 0.0, "min": 0.0,
                          "max": 0.0, "buckets": {}})
                s[part] = val
    for base, by_key in cum.items():
        for bare, entries in by_key.items():
            s = out[base]["series"].setdefault(
                bare, {"count": 0, "sum": 0.0, "min": 0.0,
                       "max": 0.0, "buckets": {}})
            prev = 0.0
            for ub, le, val in sorted(entries, key=lambda e: e[0]):
                s["buckets"][le] = val - prev
                prev = val
    return out


def merged_percentile(series: Dict[str, Any], q: float) -> float:
    """The q-th percentile of a merged histogram series — the same
    interpolation ``Histogram.percentile`` runs on a live series
    (rank over per-bucket counts, linear within the winning bucket,
    clamped to observed min/max, observed max as the +Inf bucket's
    upper edge), so a fleet merge reproduces the union registry's
    percentiles exactly."""
    count = int(series.get("count", 0))
    if not count:
        return 0.0
    items = sorted(series["buckets"].items(),
                   key=lambda kv: math.inf if kv[0] == "+Inf"
                   else float(kv[0]))
    edges = [math.inf if le == "+Inf" else float(le)
             for le, _c in items]
    counts = [c for _le, c in items]
    smin = float(series.get("min", 0.0))
    smax = float(series.get("max", 0.0))
    rank = q / 100.0 * count
    cum = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        if cum + c >= rank:
            lo = 0.0 if i == 0 else edges[i - 1]
            hi = edges[i] if edges[i] != math.inf else smax
            frac = (rank - cum) / c
            v = lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            return min(max(v, smin), smax)
        cum += c
    return smax


# ---------------------------------------------------------------------------
# the collector
# ---------------------------------------------------------------------------
class FleetCollector:
    """Scrape-or-push collector over N member expositions.

    >>> col = FleetCollector()
    >>> col.add_member("host0", "http://127.0.0.1:9100")   # pull
    >>> col.ingest("host1", exposition_text, healthz=doc)  # push
    >>> col.scrape()
    >>> print(col.fleet_prometheus_text())
    >>> col.fleet_healthz()["status"]
    'ok'
    """

    def __init__(self, stale_after_s: float = DEFAULT_STALE_AFTER_S,
                 scrape_timeout_s: float = 2.0,
                 registry: Optional[MetricsRegistry] = None):
        from .catalog import fleet_metrics

        self.stale_after_s = float(stale_after_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self._metrics = fleet_metrics(registry or get_registry())
        self._lock = threading.Lock()
        # name -> base url (None = push-only member)
        self._members: Dict[str, Optional[str]] = {}
        # name -> {"text", "healthz", "ts", "error"}
        self._state: Dict[str, Dict[str, Any]] = {}

    # -- membership ------------------------------------------------------
    def add_member(self, name: str, url: Optional[str] = None) -> None:
        """Register a member: ``url`` = scrape target base (its
        ``/metrics`` and ``/healthz`` are fetched by ``scrape()``);
        None = push-only (``ingest`` / ``POST /push`` feeds it)."""
        with self._lock:
            self._members[str(name)] = \
                url.rstrip("/") if url is not None else None

    def remove_member(self, name: str) -> None:
        with self._lock:
            self._members.pop(name, None)
            self._state.pop(name, None)

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    # -- ingestion -------------------------------------------------------
    def ingest(self, name: str, text: str,
               healthz: Optional[Dict[str, Any]] = None) -> None:
        """Push-mode ingestion of one member's exposition text (and
        optionally its /healthz doc). Unknown members are auto-added
        as push-only."""
        now = time.time()
        with self._lock:
            self._members.setdefault(str(name), None)
            self._state[str(name)] = {"text": str(text),
                                      "healthz": healthz,
                                      "ts": now, "error": None}

    def _fetch(self, url: str) -> str:
        with urllib.request.urlopen(
                url, timeout=self.scrape_timeout_s) as resp:
            return resp.read().decode("utf-8")

    def scrape(self) -> Dict[str, Optional[str]]:
        """One scrape sweep over every url-bearing member (push-only
        members keep their last ingested text). Network I/O runs with
        NO lock held; results land atomically per member. Returns
        {member: error-or-None}."""
        t0 = time.perf_counter()
        with self._lock:
            targets = [(n, u) for n, u in self._members.items()
                       if u is not None]
        results: Dict[str, Optional[str]] = {}
        m = self._metrics
        for name, url in targets:
            err: Optional[str] = None
            text, hz = "", None
            try:
                text = self._fetch(url + "/metrics")
                try:
                    hz = json.loads(self._fetch(url + "/healthz"))
                except (OSError, ValueError):
                    hz = None       # metrics up, healthz missing: ok
            except OSError as e:
                err = str(e)
            now = time.time()
            with self._lock:
                if err is None:
                    self._state[name] = {"text": text, "healthz": hz,
                                         "ts": now, "error": None}
                else:
                    st = self._state.setdefault(
                        name, {"text": "", "healthz": None,
                               "ts": None, "error": None})
                    st["error"] = err
            m["scrapes"].inc(result="error" if err else "ok")
            results[name] = err
        m["collect_seconds"].set(time.perf_counter() - t0)
        return results

    # -- merged views ----------------------------------------------------
    def _snapshot_state(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {n: dict(st) for n, st in self._state.items()}

    def merged(self) -> Dict[str, Dict[str, Any]]:
        """The structured fleet merge::

            {name: {"type": t,
                    "hosts": {host: {labelkey: value-or-hist}},
                    "fleet": {labelkey: merged-value}}}

        Counters: ``fleet`` holds the sum. Gauges: ``fleet`` holds
        ``{"min", "max", "mean"}``. Histograms: ``fleet`` holds the
        bucket-exact merged state (``merged_percentile`` applies)."""
        state = self._snapshot_state()
        out: Dict[str, Dict[str, Any]] = {}
        n_series = 0
        for host in sorted(state):
            st = state[host]
            if not st.get("text"):
                continue
            for name, fam in parse_exposition(st["text"]).items():
                dst = out.setdefault(
                    name, {"type": fam["type"], "hosts": {},
                           "fleet": {}})
                dst["hosts"][host] = fam["series"]
                n_series += len(fam["series"])
        for name, dst in out.items():
            agg: Dict[Tuple, Any] = dst["fleet"]
            for host, series in dst["hosts"].items():
                for key, val in series.items():
                    if dst["type"] == "histogram":
                        cur = agg.setdefault(
                            key, {"count": 0, "sum": 0.0,
                                  "min": math.inf, "max": -math.inf,
                                  "buckets": {}})
                        cur["count"] += int(val["count"])
                        cur["sum"] += float(val["sum"])
                        if val["count"]:
                            cur["min"] = min(cur["min"], val["min"])
                            cur["max"] = max(cur["max"], val["max"])
                        for le, c in val["buckets"].items():
                            cur["buckets"][le] = \
                                cur["buckets"].get(le, 0.0) + c
                    elif dst["type"] == "counter":
                        agg[key] = agg.get(key, 0.0) + float(val)
                    else:
                        cur = agg.setdefault(
                            key, {"min": math.inf, "max": -math.inf,
                                  "_sum": 0.0, "_n": 0})
                        cur["min"] = min(cur["min"], float(val))
                        cur["max"] = max(cur["max"], float(val))
                        cur["_sum"] += float(val)
                        cur["_n"] += 1
            if dst["type"] == "histogram":
                for cur in agg.values():
                    if not cur["count"]:
                        cur["min"] = cur["max"] = 0.0
            elif dst["type"] == "gauge":
                for key, cur in agg.items():
                    agg[key] = {"min": cur["min"], "max": cur["max"],
                                "mean": cur["_sum"] / cur["_n"]}
        self._metrics["series"].set(n_series)
        return out

    def fleet_prometheus_text(self) -> str:
        """Merged exposition: every member series re-labeled with
        ``host=<member>``, plus aggregate rows labeled
        ``host="fleet"`` (counters: the sum; gauges: one row per
        ``stat`` in min/max/mean; histograms: the bucket-exact merged
        family with cumulative ``_bucket`` rows and ``_min``/``_max``
        extension rows)."""
        from .metrics import _fmt_labels

        merged = self.merged()
        lines: List[str] = []
        for name in sorted(merged):
            fam = merged[name]
            lines.append(f"# TYPE {name} {fam['type']}")
            rows: List[Tuple[Dict[str, str], Any]] = []
            for host in sorted(fam["hosts"]):
                for key, val in sorted(fam["hosts"][host].items()):
                    rows.append(({**dict(key), "host": host}, val))
            if fam["type"] == "histogram":
                for key, val in sorted(fam["fleet"].items()):
                    rows.append(({**dict(key), "host": "fleet"}, val))
                for labels, s in rows:
                    items = sorted(
                        s["buckets"].items(),
                        key=lambda kv: math.inf if kv[0] == "+Inf"
                        else float(kv[0]))
                    cum = 0.0
                    for le, c in items:
                        cum += c
                        lbl = _fmt_labels({**labels, "le": le})
                        lines.append(f"{name}_bucket{lbl} {cum:.9g}")
                    lbl = _fmt_labels(labels)
                    lines.append(f"{name}_sum{lbl} {s['sum']:.9g}")
                    lines.append(
                        f"{name}_count{lbl} {s['count']:.9g}")
                    if s["count"]:
                        # repr keeps the extrema round-trip exact
                        # through chained collectors
                        lines.append(
                            f"{name}_min{lbl} {float(s['min'])!r}")
                        lines.append(
                            f"{name}_max{lbl} {float(s['max'])!r}")
            elif fam["type"] == "counter":
                for key, val in sorted(fam["fleet"].items()):
                    rows.append(({**dict(key), "host": "fleet"}, val))
                for labels, val in rows:
                    lines.append(
                        f"{name}{_fmt_labels(labels)} {val:.9g}")
            else:
                for labels, val in rows:
                    lines.append(
                        f"{name}{_fmt_labels(labels)} {val:.9g}")
                for key, stats in sorted(fam["fleet"].items()):
                    for stat in ("min", "max", "mean"):
                        lbl = _fmt_labels({**dict(key),
                                           "host": "fleet",
                                           "stat": stat})
                        lines.append(f"{name}{lbl} {stats[stat]:.9g}")
        return "\n".join(lines) + "\n"

    # -- health rollup ---------------------------------------------------
    def member_health(self, name: str) -> Dict[str, Any]:
        """One member's verdict: ``ok``, or ``degraded`` with a
        reason (member-reported degradation / unreachable / stale
        liveness age)."""
        now = time.time()
        with self._lock:
            st = self._state.get(name)
            known = name in self._members
        if not known and st is None:
            return {"status": "degraded", "reason": "unknown member"}
        if st is None or (st.get("error") and st.get("ts") is None):
            return {"status": "degraded", "reason": "unreachable",
                    "error": None if st is None else st["error"]}
        doc = st.get("healthz") or {}
        out: Dict[str, Any] = {"status": "ok"}
        age = doc.get("snapshot_age_seconds")
        if age is None and st.get("ts") is not None:
            # push mode (or healthz-less member): staleness = time
            # since the collector last heard from it
            age = now - st["ts"]
        if age is not None:
            out["snapshot_age_seconds"] = round(float(age), 3)
        if st.get("error"):
            out.update(status="degraded", reason="unreachable",
                       error=st["error"])
        elif doc.get("status", "ok") != "ok":
            out.update(status="degraded", reason="member degraded")
            if doc.get("components"):
                out["components"] = doc["components"]
        elif age is not None and age > self.stale_after_s:
            out.update(status="degraded", reason="stale")
        return out

    def fleet_healthz(self) -> Dict[str, Any]:
        """The fleet rollup: degraded when ANY member is degraded,
        unreachable, or stale (one sick host names the fleet sick —
        a router must know before it routes)."""
        with self._lock:
            names = sorted(set(self._members) | set(self._state))
        members = {n: self.member_health(n) for n in names}
        n_bad = sum(1 for v in members.values()
                    if v["status"] != "ok")
        m = self._metrics
        m["members"].set(len(members) - n_bad, state="ok")
        m["members"].set(n_bad, state="degraded")
        return {"status": "degraded" if n_bad else "ok",
                "members": members}

    # -- HTTP front door -------------------------------------------------
    def serve(self, port: int = 0, host: str = "127.0.0.1",
              scrape_on_get: bool = True) -> "FleetServer":
        """Serve the merged fleet view: ``GET /metrics`` (merged
        exposition; triggers a scrape sweep first unless
        ``scrape_on_get=False``), ``GET /healthz`` (the rollup),
        ``POST /push?host=<name>`` (push-mode exposition body;
        JSON ``{"host", "metrics", "healthz"}`` also accepted)."""
        return FleetServer(self, port=port, host=host,
                           scrape_on_get=scrape_on_get)


class FleetServer:
    """Handle on a running fleet collector endpoint (``port`` is the
    bound port; ``close()`` shuts the listener down)."""

    def __init__(self, collector: FleetCollector, port: int = 0,
                 host: str = "127.0.0.1", scrape_on_get: bool = True):
        col = collector

        class _Handler(BaseHTTPRequestHandler):
            def _reply(self, body: bytes, ctype: str,
                       code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    if scrape_on_get:
                        col.scrape()
                    body = json.dumps(col.fleet_healthz()) \
                        .encode("utf-8")
                    self._reply(body,
                                "application/json; charset=utf-8")
                elif path in ("/", "/metrics"):
                    if scrape_on_get:
                        col.scrape()
                    self._reply(
                        col.fleet_prometheus_text().encode("utf-8"),
                        CONTENT_TYPE)
                else:
                    self.send_error(
                        404, "only /metrics, /healthz and POST "
                             "/push are served")

            def do_POST(self):
                parsed = urlparse(self.path)
                if parsed.path != "/push":
                    self.send_error(404, "POST /push only")
                    return
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n).decode("utf-8")
                ctype = self.headers.get("Content-Type", "")
                if ctype.startswith("application/json"):
                    try:
                        doc = json.loads(raw)
                        col.ingest(doc["host"],
                                   doc.get("metrics", ""),
                                   healthz=doc.get("healthz"))
                    except (ValueError, KeyError, TypeError):
                        self.send_error(400, "bad push JSON")
                        return
                else:
                    hosts = parse_qs(parsed.query).get("host")
                    if not hosts:
                        self.send_error(400, "?host=<name> required")
                        return
                    col.ingest(hosts[0], raw)
                self._reply(b'{"ok": true}',
                            "application/json; charset=utf-8")

            def log_message(self, fmt, *args):
                pass            # scrapes must not spam the log

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-collector",
            daemon=True)
        self._thread.start()
        self.port = int(self._httpd.server_address[1])

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "FleetServer":
        return self

    def __exit__(self, *exc):
        self.close()
        return False
