"""Training health monitor: rolling robust anomaly detection over
loss, global grad-norm, and step time.

Production stability monitoring (the TeleChat3-class training reports)
is event-shaped: a human asks "did anything go wrong overnight", not
"what was the loss at step 41237". This module watches the per-step
scalars the engine already fetches (one-step lag, never on the hot
path) with ROBUST rolling statistics — median + MAD over a bounded
window, so a single outlier cannot drag the baseline the way a mean/
stddev would — and publishes **events**, not curves:

- ``loss_spike`` / ``grad_norm_spike``: the value sits more than
  ``z_threshold`` robust z-scores above the window median AND more
  than ``min_rel`` relatively above it (the second guard keeps a
  near-constant window, where MAD ~ 0, from flagging noise),
- ``loss_nonfinite``: NaN/Inf loss — always an event, no statistics,
- ``step_time_stall``: step time blows past the same two guards with
  deliberately coarser defaults (host noise is real; a stall is 3x,
  not 10%).

On an event: it lands in a bounded ring + the
``paddle_tpu_health_events_total{kind}`` counter, is journaled to the
attached goodput ledger (run_report draws the timeline), flips
``/healthz`` to degraded for ``degraded_window_s`` via the exporter's
provider protocol, and — for loss/grad events — dumps a stall-style
flight record (rate-limited) so the post-mortem holds the metric ring
around the spike.

Detection arms only after ``warmup`` observations per signal, so the
deterministic bench/smoke lines (a handful of steps) run entirely
unarmed and MUST report zero events — ``bench_compare`` gates the
``*_health_spike_events`` lines at exactly 0.

Deliberate spike injection for tests rides the failpoint table
(``health.loss_spike=corrupt@N`` perturbs the N-th OBSERVED loss —
telemetry-only: the training state never sees it).

Cross-host stragglers: ``observe_pod_skew`` all-gathers the local step
time across processes (the ``pod_throughput`` pattern — call BETWEEN
steps) and publishes ``step_time_skew`` = (slowest - median) / median
plus the slowest host id.

Everything is host-side python on fetched scalars; nothing here adds
ops to compiled programs.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["RollingRobust", "HealthMonitor", "get_monitor",
           "reset_monitor"]

# 1.4826 * MAD estimates the stddev of a normal sample — the usual
# consistency constant, so z_threshold reads in "sigmas"
_MAD_SIGMA = 1.4826


class RollingRobust:
    """Bounded window with median + MAD (both O(W log W) on demand —
    W is small; one evaluation per step is noise). Window reads copy
    under a lock: the train loop pushes while sampler/monitor threads
    may evaluate."""

    def __init__(self, window: int = 32):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=int(window))

    def __len__(self):
        with self._lock:
            return len(self._buf)

    def push(self, v: float) -> None:
        with self._lock:
            self._buf.append(float(v))

    def median_mad(self):
        """(median, MAD) of the current window; (0, 0) when empty."""
        with self._lock:
            xs = sorted(self._buf)
        if not xs:
            return 0.0, 0.0
        med = _median(xs)
        mad = _median(sorted(abs(x - med) for x in xs))
        return med, mad

    def zscore(self, v: float) -> float:
        """Robust z of ``v`` against the window (0 when unarmed)."""
        if not len(self):
            return 0.0
        med, mad = self.median_mad()
        sigma = _MAD_SIGMA * mad
        if sigma <= 0.0:
            sigma = max(abs(med) * 1e-3, 1e-12)
        return (float(v) - med) / sigma


def _median(xs: List[float]) -> float:
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


class _Signal:
    __slots__ = ("name", "window", "z_threshold", "min_rel", "flight")

    def __init__(self, name, window, z_threshold, min_rel, flight):
        self.name = name
        self.window = window
        self.z_threshold = z_threshold
        self.min_rel = min_rel
        self.flight = flight


class HealthMonitor:
    """Rolling spike/stall detection + the health event ring.

    Defaults are deliberately conservative: a real loss spike (the
    classic data-corruption / optimizer-blow-up signature) is orders
    of magnitude, not percent — ``z_threshold=6`` with ``min_rel=0.5``
    catches it the step it lands while a smoothly-descending curve
    (which only moves DOWN relative to its median) never fires.
    """

    def __init__(self, window: int = 32, warmup: int = 8,
                 z_threshold: float = 6.0, min_rel: float = 0.5,
                 step_time_z: float = 8.0, step_time_min_rel: float = 2.0,
                 event_ring: int = 256, degraded_window_s: float = 60.0,
                 flight_min_interval_s: float = 30.0,
                 flight_on_spike: bool = True):
        self.warmup = max(int(warmup), 1)
        self.degraded_window_s = float(degraded_window_s)
        self.flight_on_spike = bool(flight_on_spike)
        self.flight_min_interval_s = float(flight_min_interval_s)
        self._signals = {
            "loss": _Signal("loss", RollingRobust(window), z_threshold,
                            min_rel, True),
            "grad_norm": _Signal("grad_norm", RollingRobust(window),
                                 z_threshold, min_rel, True),
            "step_time": _Signal("step_time", RollingRobust(window),
                                 step_time_z, step_time_min_rel, False),
        }
        self._events: deque = deque(maxlen=int(event_ring))
        self._lock = threading.Lock()
        self._last_event_ts: Optional[float] = None
        self._last_flight_ts: Optional[float] = None
        self.last_flight_record: Optional[str] = None
        self._reg = None
        self._m: Dict[str, Any] = {}

    # -- metric plumbing -------------------------------------------------
    def _metrics(self) -> Dict[str, Any]:
        """health_* instruments against the CURRENT global registry
        (re-fetched after a reset_registry so long-lived monitors keep
        publishing into the registry that is actually exported)."""
        from .catalog import health_metrics
        from .metrics import get_registry

        reg = get_registry()
        if reg is not self._reg:
            self._m = health_metrics(reg)
            self._reg = reg
        return self._m

    # -- observation -----------------------------------------------------
    def observe(self, loss: Optional[float] = None,
                grad_norm: Optional[float] = None,
                step_seconds: Optional[float] = None,
                step: Optional[int] = None) -> List[Dict[str, Any]]:
        """Feed the per-step scalars (any subset); returns the events
        this observation raised (usually [])."""
        from ..distributed import failpoints as _fp

        m = self._metrics()
        fired: List[Dict[str, Any]] = []
        if loss is not None and _fp.active("health.loss_spike"):
            # deterministic telemetry-only spike injection: fires on
            # the armed corrupt action's @n schedule
            if _fp.hit("health.loss_spike", b"\0") != b"\0":
                loss = abs(float(loss)) * 1e3 + 1e3
        if loss is not None and not math.isfinite(float(loss)):
            fired.append(self._event("loss_nonfinite", float("nan"),
                                     0.0, 0.0, 0.0, step))
            loss = None
        for name, value, gauge, kind in (
                ("loss", loss, "loss_z", "loss_spike"),
                ("grad_norm", grad_norm, "grad_norm_z",
                 "grad_norm_spike"),
                ("step_time", step_seconds, "step_time_z",
                 "step_time_stall")):
            if value is None:
                continue
            value = float(value)
            sig = self._signals[name]
            armed = len(sig.window) >= self.warmup
            z = sig.window.zscore(value) if armed else 0.0
            m[gauge].set(z)
            med, mad = sig.window.median_mad()
            # one-sided: only an UPWARD excursion is an anomaly (loss
            # and grad norm falling, or steps speeding up, is health)
            if armed and z > sig.z_threshold and \
                    value > med * (1.0 + sig.min_rel) + 1e-12:
                fired.append(self._event(kind, value, med, mad, z,
                                         step, flight=sig.flight))
            sig.window.push(value)
        m["degraded"].set(1.0 if self.status() != "ok" else 0.0)
        return fired

    def _event(self, kind: str, value: float, median: float,
               mad: float, z: float, step: Optional[int],
               flight: bool = False) -> Dict[str, Any]:
        now = time.time()
        ev: Dict[str, Any] = {"kind": kind, "ts": now,
                              "value": value, "median": median,
                              "mad": mad, "z": round(z, 2)}
        if step is not None:
            ev["step"] = int(step)
        m = self._metrics()
        m["events"].inc(kind=kind)
        # the spike post-mortem: a flight record freezes the metric
        # ring + thread/region state around the event (rate-limited so
        # a spiking run does not bury the disk in dumps)
        dump_now = False
        if flight and self.flight_on_spike:
            # atomic check-and-reserve of the rate-limit slot: two
            # concurrent observers must not both dump
            with self._lock:
                dump_now = (self._last_flight_ts is None or
                            now - self._last_flight_ts >=
                            self.flight_min_interval_s)
                if dump_now:
                    self._last_flight_ts = now
        if dump_now:
            try:
                from . import flight as _flight

                record = _flight.dump(
                    reason=f"healthmon: {kind} value={value:.6g} "
                           f"median={median:.6g} z={z:.1f}"
                           + (f" step={step}" if step is not None
                              else ""))
                ev["flight_record"] = record
                with self._lock:
                    self.last_flight_record = record
            except Exception:
                pass    # the post-mortem must never take the run down
        # durable: the goodput journal carries the event timeline
        try:
            from . import goodput as _gp

            _gp.note_event(kind, **{k: v for k, v in ev.items()
                                    if k != "kind"})
        except Exception:
            pass
        with self._lock:
            self._events.append(ev)
            self._last_event_ts = now
        return ev

    # -- health surface --------------------------------------------------
    def status(self) -> str:
        """"ok", or "degraded" within ``degraded_window_s`` of the last
        event — surfaced on /healthz via the exporter provider."""
        with self._lock:
            last = self._last_event_ts
        if last is not None and \
                time.time() - last <= self.degraded_window_s:
            return "degraded"
        return "ok"

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def event_count(self, kind: Optional[str] = None) -> int:
        with self._lock:
            return sum(1 for e in self._events
                       if kind is None or e["kind"] == kind)

    def reset(self) -> None:
        """Drop windows, events, and the degraded state (tests)."""
        with self._lock:
            for sig in self._signals.values():
                sig.window = RollingRobust(sig.window._buf.maxlen)
            self._events.clear()
            self._last_event_ts = None
            self._last_flight_ts = None

    def register_healthz(self, component: str = "healthmon"):
        """Register this monitor as a /healthz component (weakref: the
        provider prunes itself once the owner is gone). Engines call
        this with their own per-run monitor so a spike degrades the
        endpoint without sharing detection windows across runs."""
        import weakref

        from . import exporter as _exporter

        ref = weakref.ref(self)

        def _provider():
            mon = ref()
            if mon is None:
                return None
            return {"component": component, "status": mon.status()}

        _exporter.add_health_provider(_provider)
        return _provider

    # -- cross-host stragglers -------------------------------------------
    def observe_pod_skew(self, step_seconds: float) -> Dict[str, float]:
        """All-gather every host's local step time (the pod_throughput
        pattern — synchronizes all processes, call BETWEEN steps) and
        publish the straggler gauges: ``step_time_skew`` = (slowest -
        median) / median, ``slowest_host`` = its process index.
        Single-process: skew 0, host 0."""
        import jax

        m = self._metrics()
        if jax.process_count() == 1:
            times = [float(step_seconds)]
        else:
            import numpy as np
            from jax.experimental import multihost_utils as mh

            times = [float(v) for v in np.asarray(
                mh.process_allgather(
                    np.asarray(float(step_seconds)))).reshape(-1)]
        med = _median(sorted(times))
        slowest = max(range(len(times)), key=lambda i: times[i])
        skew = (times[slowest] - med) / med if med > 0 else 0.0
        m["step_time_skew"].set(skew)
        m["slowest_host"].set(float(slowest))
        return {"step_time_skew": skew,
                "slowest_host": float(slowest),
                "host_step_seconds": times}


# ---------------------------------------------------------------------------
# the process-wide default monitor (standalone/manual use; /healthz
# reports it). ParallelEngine deliberately does NOT use it: each engine
# owns a PER-RUN HealthMonitor so detection windows never mix runs —
# a fresh model's first loss judged against another run's converged
# baseline would be a guaranteed false spike.
# ---------------------------------------------------------------------------
_monitor: Optional[HealthMonitor] = None
_monitor_lock = threading.Lock()


def _health_provider():
    mon = _monitor
    if mon is None:
        return None
    return {"component": "healthmon", "status": mon.status()}


def get_monitor() -> HealthMonitor:
    """The process-wide health monitor; created on first use and
    registered as a /healthz component provider."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = HealthMonitor()
            from . import exporter as _exporter

            _exporter.add_health_provider(_health_provider)
        return _monitor


def reset_monitor() -> HealthMonitor:
    """Fresh monitor state (tests): windows/events dropped, provider
    registration kept."""
    mon = get_monitor()
    mon.reset()
    return mon
