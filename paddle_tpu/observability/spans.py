"""Per-request lifecycle spans for the serving engine.

Every ``ServingRequest`` gets a ``RequestTrace``: a list of named spans
(queued → prefill → decode, plus one span per shared decode round the
request was in flight for — and, under chunked prefill, one
``prefill_chunk`` span per scheduled chunk carrying the chunk index +
token count, plus ``preempt`` instants when a page-starved row bounces
back to the queue) on the ``time.perf_counter`` clock. Finished traces
land in a bounded ``SpanRing`` so a long-running engine keeps the
last-N request histories without growing memory. The Chrome export thus
shows chunk scheduling interleaved with the decode rounds; TTFT stays
defined as first-token time (the ``prefill`` stage span closes when the
last chunk samples, not per chunk).

Exports:

- ``SpanRing.to_chrome_trace()`` — Chrome ``chrome://tracing`` /
  Perfetto JSON ("X" complete events, one ``tid`` lane per request,
  timestamps rebased to the earliest span), the same format the
  profiler's chrome exporter emits so both open in the same UI,
- per-stage latency percentiles via the
  ``paddle_tpu_serving_request_stage_seconds{stage}`` histogram
  (observed by the engine as each span closes) — the bench telemetry
  section carries them per line.

Every trace carries W3C-traceparent-style identity so a request can be
stitched across process boundaries (the multi-replica router / the
disaggregated prefill-decode split the ROADMAP plans): a 32-hex
``trace_id`` shared by every span of the request, a 16-hex root
``span_id`` per trace, and an optional ``parent_span_id`` naming the
caller's span in another process. ``format_traceparent`` /
``parse_traceparent`` round-trip the ``00-<trace>-<span>-01`` header
form; ``ServingEngine.submit`` accepts either piece and generates
what is missing.

Host-side python on perf_counter floats only; nothing here touches
traced code.
"""
from __future__ import annotations

import json
import os
import re
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Span", "RequestTrace", "SpanRing", "make_trace_id",
           "make_span_id", "format_traceparent", "parse_traceparent"]

# the per-request lifecycle stages, in order (the stage histogram's
# label values; "decode_round" additionally marks shared-round spans)
STAGES = ("queued", "prefill", "decode", "e2e")

# W3C trace-context identity: trace_id is 32 lowercase hex chars,
# span_id 16; the traceparent header is version 00 with the sampled
# flag set (we always record).
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
_SPAN_ID_RE = re.compile(r"^[0-9a-f]{16}$")
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def make_trace_id() -> str:
    """A fresh 32-hex W3C trace id (crypto-random, never all-zero)."""
    tid = os.urandom(16).hex()
    return tid if int(tid, 16) else make_trace_id()


def make_span_id() -> str:
    """A fresh 16-hex W3C span id."""
    sid = os.urandom(8).hex()
    return sid if int(sid, 16) else make_span_id()


def format_traceparent(trace_id: str, span_id: str) -> str:
    """``00-<trace_id>-<span_id>-01`` (version 00, sampled)."""
    if not _TRACE_ID_RE.match(trace_id):
        raise ValueError(f"invalid trace_id {trace_id!r} (want 32 hex)")
    if not _SPAN_ID_RE.match(span_id):
        raise ValueError(f"invalid span_id {span_id!r} (want 16 hex)")
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: str) -> Tuple[str, str]:
    """``(trace_id, span_id)`` out of a traceparent header; raises
    ValueError on a malformed header or an all-zero id (the spec's
    invalid sentinel)."""
    m = _TRACEPARENT_RE.match(str(header).strip().lower())
    if not m:
        raise ValueError(f"malformed traceparent {header!r}")
    _ver, trace_id, span_id, _flags = m.groups()
    if not int(trace_id, 16) or not int(span_id, 16):
        raise ValueError(f"all-zero id in traceparent {header!r}")
    return trace_id, span_id


class Span:
    """One named interval; ``end`` stays None while open. Every span
    carries its own 16-hex ``span_id`` and its parent's (the trace
    root for engine-created stage spans) so exported traces stitch
    across processes."""

    __slots__ = ("name", "t0", "t1", "meta", "span_id",
                 "parent_span_id")

    def __init__(self, name: str, t0: float,
                 t1: Optional[float] = None,
                 meta: Optional[Dict[str, Any]] = None,
                 span_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None):
        self.name = name
        self.t0 = float(t0)
        self.t1 = None if t1 is None else float(t1)
        self.meta = meta or {}
        self.span_id = span_id or make_span_id()
        self.parent_span_id = parent_span_id

    @property
    def seconds(self) -> float:
        return 0.0 if self.t1 is None else self.t1 - self.t0

    def to_dict(self) -> Dict[str, Any]:
        d = {"name": self.name, "t0": self.t0, "t1": self.t1,
             "seconds": self.seconds, "span_id": self.span_id}
        if self.parent_span_id is not None:
            d["parent_span_id"] = self.parent_span_id
        if self.meta:
            d["meta"] = dict(self.meta)
        return d


class RequestTrace:
    """The span set of one serving request (rid keys the trace).

    ``trace_id`` (32 hex, auto-generated when the caller brings none)
    names the request across processes; ``span_id`` is the trace's
    root span (every stage span created here is its child) and
    ``parent_span_id`` the submitting caller's span in ANOTHER
    process, straight off an incoming traceparent header.
    """

    __slots__ = ("rid", "spans", "meta", "trace_id", "span_id",
                 "parent_span_id")

    def __init__(self, rid: int, meta: Optional[Dict[str, Any]] = None,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None):
        self.rid = rid
        self.spans: List[Span] = []
        self.meta = meta or {}
        if trace_id is not None and not _TRACE_ID_RE.match(trace_id):
            raise ValueError(
                f"invalid trace_id {trace_id!r} (want 32 hex)")
        if parent_span_id is not None and \
                not _SPAN_ID_RE.match(parent_span_id):
            raise ValueError(
                f"invalid parent_span_id {parent_span_id!r} "
                f"(want 16 hex)")
        self.trace_id = trace_id or make_trace_id()
        self.span_id = make_span_id()
        self.parent_span_id = parent_span_id

    @property
    def traceparent(self) -> str:
        """The header to propagate DOWNSTREAM of this request (names
        this trace's root span as the parent)."""
        return format_traceparent(self.trace_id, self.span_id)

    def begin(self, name: str, t0: float,
              meta: Optional[Dict[str, Any]] = None) -> Span:
        sp = Span(name, t0, meta=meta, parent_span_id=self.span_id)
        self.spans.append(sp)
        return sp

    def end(self, name: str, t1: float) -> Optional[Span]:
        """Close the most recent open span named ``name``; returns it
        (None when no such span is open — callers treat that as a
        stage the request never entered)."""
        for sp in reversed(self.spans):
            if sp.name == name and sp.t1 is None:
                sp.t1 = float(t1)
                return sp
        return None

    def add(self, name: str, t0: float, t1: float,
            meta: Optional[Dict[str, Any]] = None) -> Span:
        sp = Span(name, t0, t1, meta, parent_span_id=self.span_id)
        self.spans.append(sp)
        return sp

    def span(self, name: str) -> Optional[Span]:
        for sp in self.spans:
            if sp.name == name:
                return sp
        return None

    def to_dict(self) -> Dict[str, Any]:
        d = {"rid": self.rid, "meta": dict(self.meta),
             "trace_id": self.trace_id, "span_id": self.span_id,
             "traceparent": self.traceparent,
             "spans": [s.to_dict() for s in self.spans]}
        if self.parent_span_id is not None:
            d["parent_span_id"] = self.parent_span_id
        return d


class SpanRing:
    """Bounded ring of finished request traces (thread-safe)."""

    def __init__(self, maxlen: int = 256):
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def add(self, trace: RequestTrace) -> None:
        with self._lock:
            self._ring.append(trace)

    def traces(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._ring)

    def to_dicts(self) -> List[Dict[str, Any]]:
        return [t.to_dict() for t in self.traces()]

    def to_chrome_trace(self, path: Optional[str] = None,
                        extra: Optional[List[RequestTrace]] = None
                        ) -> Dict[str, Any]:
        """Chrome-trace JSON of every finished trace (plus ``extra``
        in-flight ones): one ``tid`` lane per request, "X" complete
        events in microseconds rebased to the earliest span. Writes to
        ``path`` when given; always returns the dict."""
        traces = self.traces() + list(extra or [])
        events: List[Dict[str, Any]] = []
        t_base = min((s.t0 for t in traces for s in t.spans),
                     default=0.0)
        for tr in traces:
            events.append({"ph": "M", "name": "thread_name", "pid": 0,
                           "tid": tr.rid,
                           "args": {"name": f"req{tr.rid}"}})
            ident = {"trace_id": tr.trace_id, "span_id": tr.span_id}
            if tr.parent_span_id is not None:
                ident["parent_span_id"] = tr.parent_span_id
            for sp in tr.spans:
                if sp.t1 is None:
                    continue
                if sp.t1 == sp.t0:
                    # zero-length span = a point event (a shed
                    # decision, an eviction): Chrome "i" instant
                    # events render as markers instead of vanishing
                    # as 0-width "X" slices
                    events.append({
                        "ph": "i", "cat": "serving", "name": sp.name,
                        "pid": 0, "tid": tr.rid, "s": "t",
                        "ts": (sp.t0 - t_base) * 1e6,
                        "args": {**tr.meta, **sp.meta, **ident},
                    })
                    continue
                events.append({
                    "ph": "X", "cat": "serving", "name": sp.name,
                    "pid": 0, "tid": tr.rid,
                    "ts": (sp.t0 - t_base) * 1e6,
                    "dur": sp.seconds * 1e6,
                    "args": {**tr.meta, **sp.meta, **ident},
                })
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc
