"""Per-request lifecycle spans for the serving engine.

Every ``ServingRequest`` gets a ``RequestTrace``: a list of named spans
(queued → prefill → decode, plus one span per shared decode round the
request was in flight for — and, under chunked prefill, one
``prefill_chunk`` span per scheduled chunk carrying the chunk index +
token count, plus ``preempt`` instants when a page-starved row bounces
back to the queue) on the ``time.perf_counter`` clock. Finished traces
land in a bounded ``SpanRing`` so a long-running engine keeps the
last-N request histories without growing memory. The Chrome export thus
shows chunk scheduling interleaved with the decode rounds; TTFT stays
defined as first-token time (the ``prefill`` stage span closes when the
last chunk samples, not per chunk).

Exports:

- ``SpanRing.to_chrome_trace()`` — Chrome ``chrome://tracing`` /
  Perfetto JSON ("X" complete events, one ``tid`` lane per request,
  timestamps rebased to the earliest span), the same format the
  profiler's chrome exporter emits so both open in the same UI,
- per-stage latency percentiles via the
  ``paddle_tpu_serving_request_stage_seconds{stage}`` histogram
  (observed by the engine as each span closes) — the bench telemetry
  section carries them per line.

Host-side python on perf_counter floats only; nothing here touches
traced code.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["Span", "RequestTrace", "SpanRing"]

# the per-request lifecycle stages, in order (the stage histogram's
# label values; "decode_round" additionally marks shared-round spans)
STAGES = ("queued", "prefill", "decode", "e2e")


class Span:
    """One named interval; ``end`` stays None while open."""

    __slots__ = ("name", "t0", "t1", "meta")

    def __init__(self, name: str, t0: float,
                 t1: Optional[float] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t0 = float(t0)
        self.t1 = None if t1 is None else float(t1)
        self.meta = meta or {}

    @property
    def seconds(self) -> float:
        return 0.0 if self.t1 is None else self.t1 - self.t0

    def to_dict(self) -> Dict[str, Any]:
        d = {"name": self.name, "t0": self.t0, "t1": self.t1,
             "seconds": self.seconds}
        if self.meta:
            d["meta"] = dict(self.meta)
        return d


class RequestTrace:
    """The span set of one serving request (rid keys the trace)."""

    __slots__ = ("rid", "spans", "meta")

    def __init__(self, rid: int, meta: Optional[Dict[str, Any]] = None):
        self.rid = rid
        self.spans: List[Span] = []
        self.meta = meta or {}

    def begin(self, name: str, t0: float,
              meta: Optional[Dict[str, Any]] = None) -> Span:
        sp = Span(name, t0, meta=meta)
        self.spans.append(sp)
        return sp

    def end(self, name: str, t1: float) -> Optional[Span]:
        """Close the most recent open span named ``name``; returns it
        (None when no such span is open — callers treat that as a
        stage the request never entered)."""
        for sp in reversed(self.spans):
            if sp.name == name and sp.t1 is None:
                sp.t1 = float(t1)
                return sp
        return None

    def add(self, name: str, t0: float, t1: float,
            meta: Optional[Dict[str, Any]] = None) -> Span:
        sp = Span(name, t0, t1, meta)
        self.spans.append(sp)
        return sp

    def span(self, name: str) -> Optional[Span]:
        for sp in self.spans:
            if sp.name == name:
                return sp
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {"rid": self.rid, "meta": dict(self.meta),
                "spans": [s.to_dict() for s in self.spans]}


class SpanRing:
    """Bounded ring of finished request traces (thread-safe)."""

    def __init__(self, maxlen: int = 256):
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def add(self, trace: RequestTrace) -> None:
        with self._lock:
            self._ring.append(trace)

    def traces(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._ring)

    def to_dicts(self) -> List[Dict[str, Any]]:
        return [t.to_dict() for t in self.traces()]

    def to_chrome_trace(self, path: Optional[str] = None,
                        extra: Optional[List[RequestTrace]] = None
                        ) -> Dict[str, Any]:
        """Chrome-trace JSON of every finished trace (plus ``extra``
        in-flight ones): one ``tid`` lane per request, "X" complete
        events in microseconds rebased to the earliest span. Writes to
        ``path`` when given; always returns the dict."""
        traces = self.traces() + list(extra or [])
        events: List[Dict[str, Any]] = []
        t_base = min((s.t0 for t in traces for s in t.spans),
                     default=0.0)
        for tr in traces:
            events.append({"ph": "M", "name": "thread_name", "pid": 0,
                           "tid": tr.rid,
                           "args": {"name": f"req{tr.rid}"}})
            for sp in tr.spans:
                if sp.t1 is None:
                    continue
                if sp.t1 == sp.t0:
                    # zero-length span = a point event (a shed
                    # decision, an eviction): Chrome "i" instant
                    # events render as markers instead of vanishing
                    # as 0-width "X" slices
                    events.append({
                        "ph": "i", "cat": "serving", "name": sp.name,
                        "pid": 0, "tid": tr.rid, "s": "t",
                        "ts": (sp.t0 - t_base) * 1e6,
                        "args": {**tr.meta, **sp.meta},
                    })
                    continue
                events.append({
                    "ph": "X", "cat": "serving", "name": sp.name,
                    "pid": 0, "tid": tr.rid,
                    "ts": (sp.t0 - t_base) * 1e6,
                    "dur": sp.seconds * 1e6,
                    "args": {**tr.meta, **sp.meta},
                })
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc
