"""Thread-safe metrics registry: Counter / Gauge / Histogram with labels.

The production-telemetry layer the reference ships as
profiler_statistic summaries + the serving runtime's perf counters,
redesigned for a pull/push hybrid: every metric lives in one in-process
``MetricsRegistry`` and is exported three ways —

- ``snapshot()``       — the in-process API (dict of plain values; the
  flight recorder keeps the last N of these, bench.py emits them),
- ``prometheus_text()``— Prometheus/OpenMetrics text exposition for a
  scrape endpoint (``parse_prometheus_text`` round-trips it in tests),
- ``JsonlSink``        — append-one-JSON-object-per-snapshot to disk
  (the bench.py lineage: machine-parsable longitudinal records).

Histograms use FIXED buckets so percentile estimates are rank-stable
and mergeable across hosts (Megatron/vLLM-style p50/p99 TTFT / TPOT /
step-time reporting); ``percentile`` linearly interpolates within the
winning bucket. All mutation goes through one lock per registry —
ServingEngine worker threads, the watchdog monitor thread, and the
train loop share the global registry safely.

Everything here is host-side python on fetched scalars: nothing may be
called from inside traced code (tpulint's host-sync-in-jit rule guards
the call sites).
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "reset_registry", "JsonlSink",
    "parse_prometheus_text", "DEFAULT_LATENCY_BUCKETS",
]

# Fixed latency lattice (seconds): 100us .. 10min, roughly x2.5 steps.
# Wide enough for decode TPOT (~ms) through multi-host train steps (~s)
# without per-deployment tuning; fixed so percentiles stay comparable
# across runs and mergeable across hosts.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
    600.0)


def _label_key(labelnames: Sequence[str], labels: Dict[str, str]):
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match the declared "
            f"labelnames {sorted(labelnames)}")
    return tuple(str(labels[k]) for k in labelnames)


class _Metric:
    """Base: one named metric holding one series per label combination."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock: threading.Lock, unit: str = ""):
        self.name = name
        self.help = help
        self.unit = unit
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _get(self, labels: Dict[str, str]):
        key = _label_key(self.labelnames, labels)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = self._new_series()
        return s

    def _new_series(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def spec(self) -> Dict[str, Any]:
        """The schema row dashboards key on (tests pin these)."""
        return {"type": self.kind, "labels": sorted(self.labelnames),
                "unit": self.unit, "help": self.help}


class Counter(_Metric):
    """Monotonic count (requests, tokens, evictions, compiles)."""

    kind = "counter"

    def _new_series(self):
        return [0.0]

    def inc(self, n: float = 1.0, **labels):
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._get(labels)[0] += n

    def value(self, **labels) -> float:
        with self._lock:
            return self._get(labels)[0]


class Gauge(_Metric):
    """Point-in-time value (queue depth, occupancy, loss, memory)."""

    kind = "gauge"

    def _new_series(self):
        return [0.0]

    def set(self, v: float, **labels):
        with self._lock:
            self._get(labels)[0] = float(v)

    def inc(self, n: float = 1.0, **labels):
        with self._lock:
            self._get(labels)[0] += n

    def dec(self, n: float = 1.0, **labels):
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._get(labels)[0]


class _HistSeries:
    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self, nbuckets: int):
        self.counts = [0] * (nbuckets + 1)     # +1 = +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf


class Histogram(_Metric):
    """Fixed-bucket histogram with interpolated percentiles.

    ``buckets`` are inclusive upper bounds; an implicit +Inf bucket
    catches the tail. Fixed buckets keep p50/p99 stable under load and
    let pod-level aggregation sum counts across hosts.
    """

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock, unit="",
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labelnames, lock, unit)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = bs

    def _new_series(self):
        return _HistSeries(len(self.buckets))

    def observe(self, v: float, **labels):
        v = float(v)
        with self._lock:
            s = self._get(labels)
            i = 0
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    break
            else:
                i = len(self.buckets)
            s.counts[i] += 1
            s.sum += v
            s.count += 1
            s.min = min(s.min, v)
            s.max = max(s.max, v)

    def count(self, **labels) -> int:
        with self._lock:
            return self._get(labels).count

    def percentile(self, q: float, **labels) -> float:
        """Estimate the q-th percentile (q in [0, 100]) from bucket
        counts, linearly interpolated inside the winning bucket and
        clamped to the observed min/max."""
        with self._lock:
            s = self._get(labels)
            if not s.count:
                return 0.0
            rank = q / 100.0 * s.count
            cum = 0
            for i, c in enumerate(s.counts):
                if not c:
                    continue
                if cum + c >= rank:
                    lo = 0.0 if i == 0 else self.buckets[i - 1]
                    hi = (self.buckets[i] if i < len(self.buckets)
                          else s.max)
                    frac = (rank - cum) / c
                    v = lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                    return min(max(v, s.min), s.max)
                cum += c
            return s.max


class MetricsRegistry:
    """One process-wide home for every metric (thread-safe).

    ``counter``/``gauge``/``histogram`` are get-or-create: a second
    registration with the same name returns the SAME object, and a
    conflicting re-registration (different type/labels/buckets) raises —
    two subsystems can never silently fork a metric.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._flight = None          # set by flight.attach()
        # wall-clock of the last snapshot(): the engines snapshot once
        # per step/tick, so its age distinguishes a hung process from
        # an idle one (the /healthz payload, exporter.py)
        self._last_snapshot_ts: Optional[float] = None

    # -- registration ---------------------------------------------------
    def _register(self, cls, name, help, labelnames, unit, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                same = (type(m) is cls
                        and m.labelnames == tuple(labelnames)
                        and (not isinstance(m, Histogram) or
                             m.buckets == tuple(sorted(
                                 float(b) for b in kw.get(
                                     "buckets",
                                     DEFAULT_LATENCY_BUCKETS)))))
                if not same:
                    raise ValueError(
                        f"metric {name!r} re-registered with a "
                        f"conflicting spec (was {m.spec()})")
                return m
            m = cls(name, help, labelnames, self._lock, unit, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = (), unit: str = "") -> Counter:
        return self._register(Counter, name, help, labelnames, unit)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = (), unit: str = "") -> Gauge:
        return self._register(Gauge, name, help, labelnames, unit)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (), unit: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._register(Histogram, name, help, labelnames, unit,
                              buckets=buckets)

    # -- export ---------------------------------------------------------
    def snapshot(self, touch: bool = True) -> Dict[str, Any]:
        """Plain-dict view of every series (the in-process API).

        Also appended to the attached flight recorder's ring, so any
        code path that snapshots keeps the stall flight-record fresh.
        ``touch=False`` (the scrape path) skips the liveness timestamp
        so an external scraper's own reads never mask a hung engine.
        """
        out: Dict[str, Any] = {"ts": time.time(), "metrics": {}}
        with self._lock:
            for name, m in self._metrics.items():
                entry: Dict[str, Any] = dict(m.spec())
                series = []
                for key, s in m._series.items():
                    labels = dict(zip(m.labelnames, key))
                    if isinstance(m, Histogram):
                        series.append({
                            "labels": labels, "count": s.count,
                            "sum": s.sum,
                            "min": s.min if s.count else 0.0,
                            "max": s.max if s.count else 0.0,
                            "buckets": {
                                **{str(ub): c for ub, c in
                                   zip(m.buckets, s.counts)},
                                "+Inf": s.counts[-1]},
                        })
                    else:
                        series.append({"labels": labels, "value": s[0]})
                entry["series"] = series
                out["metrics"][name] = entry
        # percentiles computed outside the lock (they re-take it)
        for name, entry in out["metrics"].items():
            if entry["type"] != "histogram":
                continue
            m = self._metrics[name]
            for row in entry["series"]:
                for q in (50, 90, 99):
                    row[f"p{q}"] = m.percentile(q, **row["labels"])
        if self._flight is not None:
            self._flight.push(out)
        if touch:
            with self._lock:
                self._last_snapshot_ts = out["ts"]
        return out

    def snapshot_age_seconds(self) -> Optional[float]:
        """Seconds since the last snapshot() on this registry, or None
        before the first one — the /healthz liveness signal (an engine
        ticking keeps this fresh; a hung step lets it grow)."""
        with self._lock:
            ts = self._last_snapshot_ts
        return None if ts is None else max(time.time() - ts, 0.0)

    def schema(self) -> Dict[str, Any]:
        """{name: spec} for every registered metric — compared against
        the checked-in schema.json so dashboards don't silently break."""
        with self._lock:
            return {name: m.spec()
                    for name, m in sorted(self._metrics.items())}

    def prometheus_text(self, prefixes: Optional[Sequence[str]] = None
                        ) -> str:
        """Prometheus text exposition of the current state.

        ``prefixes`` filters the exposition to metric names starting
        with any of the given prefixes (the exporter's ``?names=``
        query) — still a ``snapshot(touch=False)`` read, so a
        filtered scrape never masks a hung engine. Histogram series
        additionally expose ``<name>_min``/``<name>_max`` rows (an
        extension beyond standard exposition): together with the
        fixed bucket lattice they make cross-host merges percentile-
        exact (observability/fleet.py)."""
        snap = self.snapshot(touch=False)
        lines: List[str] = []
        for name, entry in sorted(snap["metrics"].items()):
            if prefixes is not None and \
                    not any(name.startswith(p) for p in prefixes):
                continue
            if entry["help"]:
                lines.append(f"# HELP {name} {entry['help']}")
            lines.append(f"# TYPE {name} {entry['type']}")
            for row in entry["series"]:
                lbl = _fmt_labels(row["labels"])
                if entry["type"] == "histogram":
                    cum = 0
                    for ub, c in row["buckets"].items():
                        cum += c
                        le = _fmt_labels({**row["labels"], "le": ub})
                        lines.append(f"{name}_bucket{le} {cum}")
                    lines.append(f"{name}_sum{lbl} {row['sum']:.9g}")
                    lines.append(f"{name}_count{lbl} {row['count']}")
                    if row["count"]:
                        # repr: shortest round-trip form — the merge
                        # clamp must see the EXACT observed extrema
                        lines.append(
                            f"{name}_min{lbl} {row['min']!r}")
                        lines.append(
                            f"{name}_max{lbl} {row['max']!r}")
                else:
                    lines.append(f"{name}{lbl} {row['value']:.9g}")
        return "\n".join(lines) + "\n"


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _escape(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def parse_prometheus_text(text: str) -> Dict[str, Dict[Tuple, float]]:
    """Parse exposition text back to {name: {label-items-tuple: value}}
    (the round-trip check for the scrape endpoint; histogram buckets
    come back as <name>_bucket rows keyed on their ``le`` label)."""
    out: Dict[str, Dict[Tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, val = line.rsplit(" ", 1)
        if "{" in head:
            name, rest = head.split("{", 1)
            body = rest.rsplit("}", 1)[0]
            labels = []
            for part in _split_labels(body):
                k, v = part.split("=", 1)
                labels.append((k, v[1:-1]))
            key = tuple(sorted(labels))
        else:
            name, key = head, ()
        out.setdefault(name, {})[key] = float(val)
    return out


def _split_labels(body: str) -> List[str]:
    parts, depth, cur = [], False, []
    for ch in body:
        if ch == '"':
            depth = not depth
        if ch == "," and not depth:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts


class JsonlSink:
    """Append registry snapshots to a JSONL file (one object per line,
    the bench.py emission format). ``read`` round-trips the file."""

    def __init__(self, path: str):
        self.path = str(path)

    def write(self, snapshot: Dict[str, Any]) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(snapshot) + "\n")

    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out


_global_registry: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem instruments into."""
    global _global_registry
    with _global_lock:
        if _global_registry is None:
            _global_registry = MetricsRegistry()
            from . import flight

            flight.attach(_global_registry)
        return _global_registry


def reset_registry() -> MetricsRegistry:
    """Drop every metric (tests; a fresh registry is re-attached to the
    flight recorder so stall records keep flowing)."""
    global _global_registry
    with _global_lock:
        _global_registry = None
    return get_registry()
