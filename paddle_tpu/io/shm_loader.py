"""Multiprocess data loading over the native shared-memory ring.

(reference: python/paddle/io/dataloader/dataloader_iter.py:358
_DataLoaderIterMultiProcess — worker processes + shared-memory tensor
transport from fluid/imperative/data_loader.cc. Here the transport is
csrc/shm_ring.cpp: workers serialize collated batches straight into a
process-shared ring; the parent reorders by batch index so iteration
order matches the single-process loader exactly.)
"""
from __future__ import annotations

import ctypes
import multiprocessing as mp
import os
import pickle
from typing import Any, Dict

import numpy as np

from ..core import native
from ..tensor import Tensor

__all__ = ["iter_multiprocess", "available"]

_TIMEOUT_MS = 120_000


def available() -> bool:
    return native.load() is not None and hasattr(os, "fork")


def _to_plain(obj: Any) -> Any:
    """Tensors → ndarrays for pickling across the process boundary."""
    if isinstance(obj, Tensor):
        return {"__t__": True, "d": obj.numpy()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_plain(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_plain(v) for k, v in obj.items()}
    return obj


def _from_plain(obj: Any) -> Any:
    if isinstance(obj, dict):
        if obj.get("__t__"):
            return Tensor(__import__("jax.numpy", fromlist=["asarray"])
                          .asarray(obj["d"]))
        return {k: _from_plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_plain(v) for v in obj)
    return obj


def _worker_main(ring_name: bytes, dataset, batches, collate_fn,
                 worker_id: int, num_workers: int, init_fn):
    lib = native.load()
    h = lib.shmring_attach(ring_name)
    if not h:
        os._exit(1)
    # worker context for paddle.io.get_worker_info() inside the fork
    os.environ["PADDLE_TPU_WORKER_ID"] = str(worker_id)
    os.environ["PADDLE_TPU_NUM_WORKERS"] = str(num_workers)
    try:
        if init_fn is not None:
            init_fn(worker_id)
        for seq, batch_idx in enumerate(batches):
            if seq % num_workers != worker_id:
                continue
            items = [dataset[i] for i in batch_idx]
            payload = pickle.dumps((seq, _to_plain(collate_fn(items))),
                                   protocol=4)
            buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
            rc = lib.shmring_write(h, buf, len(payload), _TIMEOUT_MS)
            if rc != 0:
                os._exit(2)
        done = pickle.dumps(("__done__", worker_id), protocol=4)
        buf = (ctypes.c_uint8 * len(done)).from_buffer_copy(done)
        lib.shmring_write(h, buf, len(done), _TIMEOUT_MS)
    finally:
        lib.shmring_detach(h)
    os._exit(0)


def iter_multiprocess(dataset, batch_indices, collate_fn, num_workers: int,
                      ring_bytes: int = 64 << 20, worker_init_fn=None,
                      timeout_s: float = 120.0):
    """Yield collated batches in order, produced by ``num_workers``
    forked processes through the shm ring."""
    lib = native.load()
    if lib is None:
        raise RuntimeError("native shm ring unavailable")
    batches = list(batch_indices)
    name = f"/ptpu_ring_{os.getpid()}_{id(batches) & 0xffff}".encode()
    h = lib.shmring_create(name, ring_bytes)
    if not h:
        raise RuntimeError("shmring_create failed")
    ctx = mp.get_context("fork")
    procs = [ctx.Process(target=_worker_main,
                         args=(name, dataset, batches, collate_fn, w,
                               num_workers, worker_init_fn), daemon=True)
             for w in range(num_workers)]
    for p in procs:
        p.start()
    pending: Dict[int, Any] = {}
    next_seq, done = 0, 0
    out = ctypes.POINTER(ctypes.c_uint8)()
    try:
        while next_seq < len(batches):
            if next_seq in pending:
                yield pending.pop(next_seq)
                next_seq += 1
                continue
            if done >= num_workers:
                raise RuntimeError(
                    f"dataloader workers exited early: batch {next_seq} "
                    "never arrived")
            n = lib.shmring_read(h, ctypes.byref(out),
                                 int(timeout_s * 1000))
            if n < 0:
                dead = [p.exitcode for p in procs
                        if p.exitcode not in (None, 0)]
                raise RuntimeError(
                    "dataloader shm read timed out"
                    + (f"; worker exit codes {dead}" if dead else ""))
            payload = ctypes.string_at(out, n)
            lib.shmring_free(out)
            seq, batch = pickle.loads(payload)
            if seq == "__done__":
                done += 1
                continue
            pending[seq] = _from_plain(batch)
    finally:
        lib.shmring_close(h)
        for p in procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        lib.shmring_detach(h)
