"""Data loading (reference: python/paddle/io/reader.py:216 DataLoader,
io/dataloader/dataloader_iter.py — multiprocess workers feeding a queue).

TPU-native notes: batches are assembled as numpy on host (cheap) and only
cross to device HBM at first op use; a background thread prefetches so
host input pipeline overlaps device compute, the role the reference's
worker pool plays.
"""
from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Any, Iterable, List, Optional

import numpy as np

from ..core import rng as _rng
from ..tensor import Tensor

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "Subset",
           "ConcatDataset", "random_split", "BatchSampler", "Sampler",
           "SequenceSampler", "RandomSampler", "DistributedBatchSampler",
           "DataLoader", "default_collate_fn"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, tensors: List):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] if not isinstance(t, Tensor) else t.numpy()[idx]
                     for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return len(t) if not isinstance(t, Tensor) else t.shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(math.floor(len(dataset) * l)) for l in lengths]
        lengths[-1] = len(dataset) - sum(lengths[:-1])
    idx = np.random.permutation(len(dataset))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, idx[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from .. import distributed as dist

            num_replicas = num_replicas if num_replicas is not None else \
                dist.get_world_size()
            rank = rank if rank is not None else dist.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            g = np.random.RandomState(self.epoch)
            indices = g.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, Tensor):
        return Tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _iter_sync(self):
        if isinstance(self.dataset, IterableDataset):
            # batch up the stream
            it = iter(self.dataset)
            bs = self.batch_sampler.batch_size if self.batch_sampler else 1
            while True:
                items = list(itertools.islice(it, bs))
                if not items:
                    return
                yield self.collate_fn(items)
        else:
            for batch_idx in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in batch_idx])

    def __iter__(self):
        if self.num_workers and self.num_workers > 0 and \
                not isinstance(self.dataset, IterableDataset) and \
                self.batch_sampler is not None:
            from . import shm_loader

            if shm_loader.available():
                # native path: forked workers collate into the C++
                # shared-memory ring (csrc/shm_ring.cpp)
                yield from shm_loader.iter_multiprocess(
                    self.dataset, list(self.batch_sampler),
                    self.collate_fn, int(self.num_workers),
                    worker_init_fn=getattr(self, "worker_init_fn", None))
                return
        if not self.use_buffer_reader:
            yield from self._iter_sync()
            return
        # background prefetch thread (overlaps host pipeline with device)
        q: "queue.Queue" = queue.Queue(maxsize=max(2, self.prefetch_factor))
        sentinel = object()
        err: List[BaseException] = []

        def worker():
            try:
                for item in self._iter_sync():
                    q.put(item)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if err:
                    raise err[0]
                return
            yield item


class ChainDataset(IterableDataset):
    """Chain iterable datasets end to end (reference: io/dataset.py
    ChainDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds

    def __len__(self):
        # TypeError (not NotImplementedError) so list()/iteration
        # protocols treat it as unsized
        raise TypeError("ChainDataset has no len()")


class ComposeDataset(Dataset):
    """Zip map-style datasets field-wise (reference: io/dataset.py
    ComposeDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        lens = {len(d) for d in self.datasets}
        if len(lens) != 1:
            raise ValueError("ComposeDataset datasets must share length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class SubsetRandomSampler(Sampler):
    """Random permutation over a fixed index subset (reference:
    io/sampler.py SubsetRandomSampler)."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        import numpy as _np

        perm = _np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in perm])

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    """Sample indices proportionally to weights (reference:
    io/sampler.py WeightedRandomSampler)."""

    def __init__(self, weights, num_samples, replacement=True):
        import numpy as _np

        self.weights = _np.asarray(weights,
                                   dtype=_np.float64).reshape(-1)
        if (self.weights < 0).any():
            raise ValueError("weights must be non-negative")
        if self.weights.sum() == 0:
            raise ValueError("weights must not be all zero")
        self.num_samples = int(num_samples)
        self.replacement = bool(replacement)
        if not self.replacement and self.num_samples > len(self.weights):
            raise ValueError("num_samples > population without replacement")

    def __iter__(self):
        import numpy as _np

        p = self.weights / self.weights.sum()
        idx = _np.random.choice(len(p), size=self.num_samples,
                                replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


def get_worker_info():
    """(reference: io/dataloader/worker.py get_worker_info) — worker
    context inside multiprocess DataLoader workers; None in the main
    process."""
    import os as _os

    wid = _os.environ.get("PADDLE_TPU_WORKER_ID")
    if wid is None:
        return None

    class _Info:
        id = int(wid)
        num_workers = int(_os.environ.get("PADDLE_TPU_NUM_WORKERS", 1))

    return _Info()


__all__ = __all__ + ["ChainDataset", "ComposeDataset",
                     "SubsetRandomSampler", "WeightedRandomSampler",
                     "get_worker_info"]
