"""Public utilities: the custom-op extension point + cpp_extension.

(reference: python/paddle/utils/__init__.py, cpp_extension/ — and the
C++ registration surface paddle/phi/api/ext/op_meta_info.h
``PD_BUILD_OP``.)
"""
from .op_extension import (custom_op, custom_grad, custom_spmd_rule,
                           registered_ops)  # noqa: F401
from . import cpp_extension  # noqa: F401

__all__ = ["custom_op", "custom_grad", "custom_spmd_rule",
           "registered_ops", "cpp_extension"]


# utils tail (reference: python/paddle/utils/__init__.py)
def try_import(module_name, err_msg=None):
    """(reference: utils/lazy_import.py)."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or
                          f"optional dependency {module_name!r} is not "
                          f"installed") from e


def require_version(min_version, max_version=None):
    """(reference: utils/install_check.py require_version) — checks
    this package's version."""
    from .. import __version__

    def _tup(v):
        return tuple(int(x) for x in str(v).split(".")[:3])

    cur = _tup(__version__)
    if _tup(min_version) > cur:
        raise Exception(f"paddle_tpu >= {min_version} required, "
                        f"found {__version__}")
    if max_version is not None and _tup(max_version) < cur:
        raise Exception(f"paddle_tpu <= {max_version} required, "
                        f"found {__version__}")


def deprecated(update_to="", since="", reason="", level=0):
    """(reference: utils/deprecated.py) — warns once per call site."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = (f"API {fn.__module__}.{fn.__name__} is deprecated "
                   f"since {since or 'an earlier release'}")
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def run_check():
    """Install smoke check (reference: utils/install_check.py
    run_check): one tiny train step on the attached backend plus a
    mesh-sharded matmul."""
    import numpy as np

    import jax

    from .. import nn, optimizer, to_tensor

    dev = jax.devices()[0]
    m = nn.Linear(4, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = to_tensor(np.ones((2, 4), "float32"))
    loss = m(x).sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! "
          f"backend={dev.platform} devices={n}")
    return True


__all__ = __all__ + ["try_import", "require_version", "deprecated",
                     "run_check"]
