"""Public utilities: the custom-op extension point + cpp_extension.

(reference: python/paddle/utils/__init__.py, cpp_extension/ — and the
C++ registration surface paddle/phi/api/ext/op_meta_info.h
``PD_BUILD_OP``.)
"""
from .op_extension import (custom_op, custom_grad, custom_spmd_rule,
                           registered_ops)  # noqa: F401
from . import cpp_extension  # noqa: F401

__all__ = ["custom_op", "custom_grad", "custom_spmd_rule",
           "registered_ops", "cpp_extension"]
