"""Out-of-tree custom operators.

(reference: paddle/phi/api/ext/op_meta_info.h ``PD_BUILD_OP`` — C++
macro registering forward/backward/infer-meta/SPMD-rule hooks for a
custom op; python surface python/paddle/utils/cpp_extension loading a
compiled .so of such registrations.)

TPU-native redesign: a custom op is a JAX-traceable function — pure
jnp/lax code or a Pallas TPU kernel — registered into the SAME dispatch
registry as every built-in op (core/registry.py). That buys, with zero
extra machinery:

- autograd: the tape differentiates through it with generic jax.vjp,
  or an explicit backward via :func:`custom_grad` (PD_BUILD_GRAD_OP);
- jit/to_static + the distributed engines: the op traces into compiled
  steps like any built-in, and runs under shard_map (use
  ``paddle_tpu.distributed.collective`` axis helpers inside for
  explicit collectives);
- AMP lists, the profiler and the nan/inf observer, which all hook the
  dispatch chokepoint;
- eager SPMD metadata via :func:`custom_spmd_rule` (the reference's
  InferSpmdFn slot in OpMetaInfoBuilder).

Example — an out-of-tree fused op with explicit grad and SPMD rule::

    from paddle_tpu.utils import custom_op, custom_grad, custom_spmd_rule

    @custom_op("my_swiglu")
    def my_swiglu(gate, up):
        return jax.nn.silu(gate) * up

    @custom_grad("my_swiglu")
    def my_swiglu_grad(in_values, out_values, out_grads):
        g, u = in_values
        dy = out_grads  # single-output ops get the bare cotangent
        s = jax.nn.sigmoid(g)
        silu = g * s
        return (dy * u * (s + silu * (1 - s)), dy * silu)

    @custom_spmd_rule("my_swiglu")
    def my_swiglu_spmd(op, in_tensors, out_vals, args, kwargs):
        from paddle_tpu.distributed.auto_parallel.spmd_rules import _spec_of
        s = _spec_of(in_tensors[0])
        return [s] if s is not None else None

For host-side native code (IO, stores, data plumbing) compile C++ with
:mod:`paddle_tpu.utils.cpp_extension` — device code is always expressed
in JAX/Pallas, never hand-built machine kernels.
"""
from __future__ import annotations

from typing import Callable, List

from ..core.dispatch import def_grad, def_op
from ..core import registry as _registry

__all__ = ["custom_op", "custom_grad", "custom_spmd_rule",
           "registered_ops"]


def custom_op(name: str, differentiable: bool = True) -> Callable:
    """Register an out-of-tree op (reference PD_BUILD_OP). The decorated
    function takes/returns raw jax arrays; the returned public function
    takes/returns Tensors through the dispatch chokepoint."""
    return def_op(name, differentiable=differentiable)


def custom_grad(name: str) -> Callable:
    """Attach an explicit backward (reference PD_BUILD_GRAD_OP).
    Signature: fn(in_values, out_values, out_grads, **attrs) -> tuple of
    input cotangents (None allowed). Without it, generic jax.vjp
    differentiates the forward."""
    return def_grad(name)


def custom_spmd_rule(name: str) -> Callable:
    """Attach an eager sharding-propagation rule (reference
    OpMetaInfoBuilder::SetInferSpmdFn). fn(op_name, in_tensors,
    out_values, args, kwargs) -> list of PartitionSpec tuples per
    output, or None."""
    from ..distributed.auto_parallel.spmd_rules import register_rule

    return register_rule(name)


def registered_ops() -> List[str]:
    """All op names in the dispatch registry (built-in + custom)."""
    return sorted(_registry._REGISTRY.keys())
