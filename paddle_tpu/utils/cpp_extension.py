"""JIT-compiled host-side C++ extensions.

(reference: python/paddle/utils/cpp_extension/ — CppExtension /
CUDAExtension / ``load(name, sources)`` building a .so of PD_BUILD_OP
registrations with nvcc.)

TPU-native scope: there is no user device code to compile — device
kernels are JAX/Pallas (see utils/op_extension.py). What remains native
is HOST-side machinery (custom data loaders, stores, codecs: the same
role as csrc/tcp_store.cpp + shm_ring.cpp), compiled here with g++ over
the C ABI and bound via ctypes — pybind11 is deliberately not required.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import List, Optional, Sequence

from ..core.enforce import enforce

__all__ = ["load", "CppExtension", "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Build description (reference cpp_extension.CppExtension)."""

    def __init__(self, sources: Sequence[str],
                 extra_compile_args: Optional[List[str]] = None,
                 extra_link_args: Optional[List[str]] = None,
                 include_dirs: Optional[List[str]] = None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])
        self.extra_link_args = list(extra_link_args or [])
        self.include_dirs = list(include_dirs or [])


def load(name: str, sources: Sequence[str],
         extra_cxx_cflags: Optional[List[str]] = None,
         extra_ldflags: Optional[List[str]] = None,
         extra_include_paths: Optional[List[str]] = None,
         build_directory: Optional[str] = None,
         verbose: bool = False) -> ctypes.CDLL:
    """Compile C++ sources to a shared library and ctypes-load it
    (reference cpp_extension.load). Recompiles only when sources or
    flags change (content-hash keyed, like the reference's version.txt
    check)."""
    srcs = [os.path.abspath(s) for s in sources]
    for s in srcs:
        enforce(os.path.exists(s), f"source not found: {s}")
    cflags = ["-O2", "-fPIC", "-std=c++17", "-Wall"] + list(
        extra_cxx_cflags or [])
    ldflags = ["-shared", "-pthread"] + list(extra_ldflags or [])
    incs = [f"-I{p}" for p in (extra_include_paths or [])]

    h = hashlib.sha1()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(cflags + ldflags + incs).encode())
    out_dir = build_directory or get_build_directory()
    so = os.path.join(out_dir, f"{name}_{h.hexdigest()[:12]}.so")
    if not os.path.exists(so):
        cmd = (["g++"] + cflags + incs + srcs + ldflags + ["-o", so])
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        enforce(proc.returncode == 0,
                f"g++ failed for extension {name!r}:\n{proc.stderr}")
    return ctypes.CDLL(so)
