"""Pallas TPU fused RMSNorm.

(reference: phi/kernels/gpu/rms_norm_kernel.cu + rms_norm_funcs.h —
warp-reduce CUDA kernel; SPMD rule infermeta/spmd_rules/rms_norm.cc.)

One VMEM pass: f32 mean-of-squares per row, rsqrt, scale — rows tiled
(block_t, H) so the reduction stays on the VPU. Backward is the analytic
VJP computed by XLA from the same formula (memory-bound op; recompute is
free relative to HBM traffic).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from . import is_tpu_platform, pick_block

__all__ = ["rms_norm_fused", "rms_norm_supported", "rms_norm_dense"]


def _kernel(x_ref, w_ref, o_ref, *, eps):
    xf = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    o_ref[:] = (xf * lax.rsqrt(ms + eps)
                * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _pick_block(T: int) -> int:
    return pick_block(T, prefer=(256, 128, 512, 64, 32, 16, 8, 4, 2, 1))


def _rms_ref(x2, w, eps):
    xf = x2.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(ms + eps) * w.astype(jnp.float32)).astype(
        x2.dtype)


def _interpret_default() -> bool:
    return not is_tpu_platform()


def rms_norm_supported(shape) -> bool:
    """Mosaic gate for kernel-dispatch sites: True when the flattened
    row count and the hidden dim of ``shape`` tile cleanly on real TPU
    (see _mosaic_tileable).  Callers fall back to rms_norm_dense when
    this returns False."""
    H = int(shape[-1])
    T = 1
    for d in shape[:-1]:
        T *= int(d)
    return _mosaic_tileable(T, _pick_block(T), H)


def rms_norm_dense(x, weight, eps=1e-6):
    """XLA reference path — identical f32 math to the kernel, so the
    fused and dense paths are numerically interchangeable."""
    H = x.shape[-1]
    return _rms_ref(x.reshape(-1, H), weight, eps).reshape(x.shape)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm_fused(x, weight, eps=1e-6, interpret=None):
    """x: [..., H] (normalized over the last dim), weight: [H]."""
    out, _ = _fwd(x, weight, eps, interpret)
    return out


def _mosaic_tileable(T, bt, H) -> bool:
    """Real-TPU shape gate: the second-minor block dim must divide by 8
    (or equal the array dim) per the Mosaic tiling rule, and H must
    fill whole 128-wide VPU lanes — sub-lane H (tiny-model hidden 64)
    was observed to HANG the Mosaic compiler on v5e, so those shapes
    take the XLA path."""
    return (bt % 8 == 0 or bt == T) and H % 128 == 0


def _fwd(x, weight, eps, interpret):
    if interpret is None:
        interpret = _interpret_default()
    H = x.shape[-1]
    x2 = x.reshape(-1, H)
    T = x2.shape[0]
    bt = _pick_block(T)
    if not interpret and not _mosaic_tileable(T, bt, H):
        return _rms_ref(x2, weight, eps).reshape(x.shape), (x, weight)
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    out = pl.pallas_call(
        partial(_kernel, eps=eps),
        grid=(T // bt,),
        in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0), **kw),
                  pl.BlockSpec((H,), lambda i: (0,), **kw)],
        out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0), **kw),
        out_shape=jax.ShapeDtypeStruct((T, H), x.dtype),
        interpret=interpret,
    )(x2, weight)
    return out.reshape(x.shape), (x, weight)


def _bwd(eps, interpret, res, g):
    x, weight = res
    H = x.shape[-1]

    def ref(x_, w_):
        return _rms_ref(x_.reshape(-1, H), w_, eps).reshape(x_.shape)

    _, vjp_fn = jax.vjp(ref, x, weight)
    return vjp_fn(g)


rms_norm_fused.defvjp(lambda x, w, eps, interpret:
                      _fwd(x, w, eps, interpret), _bwd)
