"""Pallas TPU kernels for the hot fused ops.

(reference CUDA counterparts: phi/kernels/gpu/flash_attn_kernel.cu,
rms_norm_kernel.cu, fusion/gpu/fused_rope_kernel.cu,
fused_multi_transformer_op.cu.h — here each is a Mosaic kernel tiled for
MXU/VMEM; on non-TPU backends the callers fall back to plain XLA, and
tests run the kernels in interpret mode.)
"""
