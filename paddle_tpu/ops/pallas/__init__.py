"""Pallas TPU kernels for the hot fused ops.

(reference CUDA counterparts: phi/kernels/gpu/flash_attn_kernel.cu,
rms_norm_kernel.cu, fusion/gpu/fused_rope_kernel.cu,
fused_multi_transformer_op.cu.h — here each is a Mosaic kernel tiled for
MXU/VMEM; on non-TPU backends the callers fall back to plain XLA, and
tests run the kernels in interpret mode.)
"""
from __future__ import annotations

import jax

__all__ = ["is_tpu_platform", "pick_block"]


def is_tpu_platform() -> bool:
    """True on real TPU backends (incl. the 'axon' tunnel platform) —
    kernels compile via Mosaic; elsewhere they run in interpret mode."""
    try:
        p = str(jax.devices()[0].platform).lower()
        return "tpu" in p or "axon" in p
    except Exception:
        return False


def pick_block(n: int, prefer=(128, 256, 512, 64, 32, 16, 8)) -> int:
    """Largest MXU/VPU-aligned block size that divides ``n`` (0 = none)."""
    for b in prefer:
        if b <= n and n % b == 0:
            return b
    return 0


_BLOCKS_LARGE = (512, 256, 128, 64, 32, 16, 8)


def compiler_params(n_parallel: int, interpret: bool = False) -> dict:
    """kwargs for pallas_call telling Mosaic which grid axes are
    parallel — the streaming axis is 'arbitrary' (it carries a scratch
    recurrence). Probes the CompilerParams name across JAX versions."""
    if interpret:
        return {}
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pragma: no cover
        return {}
    sem = ("parallel",) * n_parallel + ("arbitrary",)
    for cls_name in ("CompilerParams", "TPUCompilerParams"):
        cls = getattr(pltpu, cls_name, None)
        if cls is not None:
            try:
                return {"compiler_params": cls(dimension_semantics=sem)}
            except Exception:  # pragma: no cover - API drift
                continue
    return {}
