"""Pallas TPU unified ragged paged attention: ONE kernel for mixed
prefill-chunk and decode rows over the shared KV page pool.

This is the serving-side redesign the Ragged Paged Attention paper
(PAPERS.md) builds: instead of one bucketed program per prompt prefill
plus a separate shared decode step (the head-of-line pathology — a long
prompt's prefill stalls every in-flight decode row), a single
``pallas_call`` serves a batch whose rows are RAGGED along two axes:

- ``starts[b]``   — the row's absolute cache position of its first new
  token this dispatch (prefill chunk offset, or the decode position),
- ``seq_lens[b]`` — how many of the row's ``Sb`` q slots carry real
  tokens: a prefill chunk feeds up to ``Sb``, a decode row exactly 1,
  an idle/empty slot 0 (its lane computes nothing and outputs zeros).

The row kind never reaches the kernel — decode IS a seq_len-1 chunk;
the scheduler (inference/serving.py) keeps ``kind`` host-side only.

Design, inherited from decode_attention.py's paged kernel:

- grid = (B, KV_heads, npages); the page axis streams through VMEM,
  online-softmax stats in scratch. The BlockSpec index map gathers the
  physical page id from the scalar-prefetched block table AND clamps
  the page index at each row's OWN frontier ``(start + seq_len - 1) //
  page`` — a decode row DMAs exactly the pages holding its history,
  never the ``Sb``-wide window a uniform chunk program would touch.
  That per-row clamp is where the unified program's HBM traffic comes
  in at or below the old prefill+decode two-program sum.
- causal masking is positional: q slot ``i`` of row ``b`` sits at
  absolute position ``starts[b] + i`` and attends cache positions
  ``<= starts[b] + i``; slots ``i >= seq_lens[b]`` are dead (masked
  everywhere, output zeroed).
- GQA native: the q heads of one KV group form the sublane axis, the
  pool is read once per KV head.

``ragged_paged_attention_dense`` is the XLA fallback (gather the pages,
ragged dense mask) — the CPU/tier-1 reference the kernel is
parity-gated against (bench ``serving_ragged_kernel_parity``).
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from . import compiler_params as _compiler_params, is_tpu_platform

__all__ = ["ragged_paged_attention", "ragged_paged_attention_dense",
           "ragged_supported"]

_NEG = -1e30


def _ragged_kernel(len_ref, nv_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                   m_s, l_s, acc_s, *, scale, page, npages, Sq, G):
    b = pl.program_id(0)
    j = pl.program_id(2)
    off = len_ref[b]                      # row's first q position
    nv = nv_ref[b]                        # valid q slots (0 = dead row)
    j_last = jnp.maximum(off + nv - 1, 0) // page

    @pl.when(j == 0)
    def _():
        m_s[...] = jnp.full_like(m_s, _NEG)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(j <= j_last)
    def _():
        qb = q_ref[0, :, 0, :, :].reshape(Sq * G, -1)      # [Sq*G, D]
        kb = k_ref[0, 0]                                   # [page, D]
        vb = v_ref[0, 0]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        rows = lax.broadcasted_iota(jnp.int32, (Sq * G, page), 0) // G
        cols = j * page + lax.broadcasted_iota(
            jnp.int32, (Sq * G, page), 1)
        keep = (cols <= off + rows) & (rows < nv)
        s = jnp.where(keep, s, _NEG)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_s[:, :1] = l_s[:, :1] * corr + jnp.sum(p, -1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:, :1] = m_new

    @pl.when(j == npages - 1)
    def _():
        # dead slots (nv == 0) kept l == 0 -> output exactly 0, the
        # same definition the dense fallback zero-masks to
        l = jnp.maximum(l_s[:, :1], 1e-30)
        o_ref[0, :, 0, :, :] = (acc_s[...] / l).reshape(
            Sq, G, -1).astype(o_ref.dtype)


def ragged_supported(q_shape, pool_shape) -> bool:
    """Same Mosaic gates as the paged decode kernel: whole-lane head
    dim, sublane-tileable page, q block resident in VMEM."""
    if pltpu is None:
        return False
    B, Sq, H, D = q_shape
    KV, page = pool_shape[1], pool_shape[2]
    if H % KV or D % 128 != 0:
        return False
    if page % 8 or page < 8:
        return False
    return Sq * (H // KV) <= 2048


def ragged_paged_attention(q, k_pool, v_pool, block_tables, starts,
                           seq_lens, scale=None, interpret=None):
    """Unified mixed prefill/decode attention over the paged KV pool.

    q            [B, Sb, H, D]  slot i of row b sits at absolute cache
                                position starts[b]+i; only slots
                                i < seq_lens[b] are real
    k/v_pool     [P, KV, page, D]  shared physical page pool
    block_tables [B, npages]    logical->physical page map per row
    starts       [B]            first q position per row (= tokens
                                already in cache before this dispatch)
    seq_lens     [B]            valid q slots per row: prefill chunk
                                width, 1 for decode, 0 for a dead row
                                (outputs zeros, DMAs one clamped page)
    """
    B, Sq, H, D = q.shape
    KV, page = k_pool.shape[1], k_pool.shape[2]
    npages = block_tables.shape[1]
    G = H // KV
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = not is_tpu_platform()
    q5 = q.reshape(B, Sq, KV, G, D)
    starts = jnp.asarray(starts, jnp.int32).reshape(B)
    seq_lens = jnp.asarray(seq_lens, jnp.int32).reshape(B)
    tbl = jnp.asarray(block_tables, jnp.int32).reshape(B * npages)

    def pool_index(b, h, j, ln, nv, tb):
        # clamp the streamed page index at the row's OWN frontier: a
        # decode row never DMAs the Sb-wide window a chunk row needs
        jc = jnp.minimum(j, jnp.maximum(ln[b] + nv[b] - 1, 0) // page)
        return (tb[b * npages + jc], h, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KV, npages),
        in_specs=[
            pl.BlockSpec((1, Sq, 1, G, D), lambda b, h, j, ln, nv, tb:
                         (b, 0, h, 0, 0)),
            pl.BlockSpec((1, 1, page, D), pool_index),
            pl.BlockSpec((1, 1, page, D), pool_index),
        ],
        out_specs=pl.BlockSpec((1, Sq, 1, G, D),
                               lambda b, h, j, ln, nv, tb: (b, 0, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Sq * G, 128), jnp.float32),
            pltpu.VMEM((Sq * G, 128), jnp.float32),
            pltpu.VMEM((Sq * G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        partial(_ragged_kernel, scale=scale, page=page, npages=npages,
                Sq=Sq, G=G),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, KV, G, D), q.dtype),
        interpret=interpret,
        **_compiler_params(2, interpret),
    )(starts, seq_lens, tbl, q5, k_pool, v_pool)
    return out.reshape(B, Sq, H, D)


def ragged_paged_attention_dense(q, k_pool, v_pool, block_tables,
                                 starts, seq_lens):
    """XLA reference/fallback: gather the pages into a contiguous view,
    run the doubly-ragged dense mask, zero the dead q slots (matching
    the kernel's l==0 -> 0 definition exactly)."""
    B, Sq, H, D = q.shape
    page = k_pool.shape[2]
    npages = block_tables.shape[1]

    def gather(pool):
        g = pool[block_tables]                  # [B, npages, KV, page, D]
        g = jnp.swapaxes(g, 1, 2)               # [B, KV, npages, page, D]
        return g.reshape(B, pool.shape[1], npages * page, D)

    k_cache, v_cache = gather(k_pool), gather(v_pool)
    KV, M = k_cache.shape[1], k_cache.shape[2]
    rep = H // KV
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)          # [B, H, Sq, D]
    qf = qf.reshape(B, KV, rep, Sq, D)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    scores = jnp.einsum("bkrsd,bkmd->bkrsm", qf, kf) / np.sqrt(D)
    off = jnp.asarray(starts, jnp.int32).reshape(B)
    nv = jnp.asarray(seq_lens, jnp.int32).reshape(B)
    q_pos = off[:, None] + jnp.arange(Sq)[None, :]           # [B, Sq]
    alive = jnp.arange(Sq)[None, :] < nv[:, None]            # [B, Sq]
    keep = (jnp.arange(M)[None, None, :] <= q_pos[:, :, None]) \
        & alive[:, :, None]
    scores = jnp.where(keep[:, None, None], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkrsm,bkmd->bkrsd", probs, vf)
    out = jnp.where(alive[:, None, None, :, None], out, 0.0)
    return jnp.swapaxes(out.reshape(B, H, Sq, D), 1, 2).astype(q.dtype)
