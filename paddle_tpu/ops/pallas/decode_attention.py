"""Pallas TPU decode attention: short q against a long KV cache.

TPU-native replacement for the reference's CUDA decode kernels
(reference: fluid/operators/fused/fused_multi_transformer_op.cu.h —
the 2,023-LoC masked cache-KV decoder loop — and
phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu, the
paged/block KV-cache attention kernel).

Design — the cache STREAMS through VMEM in blocks as the innermost grid
dimension; nothing is ever resident at O(cache_len):

- grid = (B, KV_heads, cache_blocks). Online-softmax statistics and the
  output accumulator live in VMEM scratch, carried across the
  sequentially-iterated cache-block axis.
- the valid cache length (``offset`` + new tokens) is a SCALAR-PREFETCH
  input: the BlockSpec index maps clamp the cache block index to the
  last valid block, so blocks past the frontier are never DMA'd from
  HBM — the TPU equivalent of the paged kernel only touching mapped
  pages. Compute for those steps is skipped with ``pl.when``.
- GQA is native: the q heads of one KV group form the sublane axis of a
  single [Sq*G, D] block, so the cache is read once per KV head (the
  dense fallback repeats it per q head).

The q rows sit at absolute positions offset..offset+Sq-1 and attend to
cache positions <= their own (causal within the freshly-appended chunk,
everything before ``offset`` visible). This covers both decode (Sq=1)
and chunked prefill (Sq=block).

Layout: q [B, Sq, H, D], caches [B, KV, M, D] — head-major so each
head's [M, D] plane is a contiguous Mosaic-tileable block (the
static-shape cache layout of models/llama.py).
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from . import (_BLOCKS_LARGE as _BLOCKS, compiler_params as
               _compiler_params, is_tpu_platform, pick_block as _pick_block)

__all__ = ["decode_attention", "paged_decode_attention",
           "paged_attention_dense", "paged_supported"]

_NEG = -1e30


def _kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s, *,
            scale, block_kv, nkv, Sq, G):
    j = pl.program_id(2)
    off = len_ref[pl.program_id(0)]       # this row's q start (ragged)
    j_last = (off + Sq - 1) // block_kv   # last cache block with valid cols

    @pl.when(j == 0)
    def _():
        m_s[...] = jnp.full_like(m_s, _NEG)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(j <= j_last)
    def _():
        qb = q_ref[0, :, 0, :, :].reshape(Sq * G, -1)      # [Sq*G, D]
        kb = k_ref[0, 0]                                   # [bkv, D]
        vb = v_ref[0, 0]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        rows = lax.broadcasted_iota(jnp.int32, (Sq * G, block_kv), 0) // G
        cols = j * block_kv + lax.broadcasted_iota(
            jnp.int32, (Sq * G, block_kv), 1)
        keep = cols <= off + rows
        s = jnp.where(keep, s, _NEG)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_s[:, :1] = l_s[:, :1] * corr + jnp.sum(p, -1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:, :1] = m_new

    @pl.when(j == nkv - 1)
    def _():
        l = jnp.maximum(l_s[:, :1], 1e-30)
        o_ref[0, :, 0, :, :] = (acc_s[...] / l).reshape(
            Sq, G, -1).astype(o_ref.dtype)


def supported(q_shape, cache_shape) -> bool:
    if pltpu is None:  # no TPU pallas backend
        return False
    B, Sq, H, D = q_shape
    KV, M = cache_shape[1], cache_shape[2]
    if H % KV or _pick_block(M, prefer=_BLOCKS) <= 0:
        return False
    # D must fill whole VPU lanes: the in-kernel [Sq,G,D]->[Sq*G,D]
    # reshape with sub-lane D (e.g. tiny-model D=16) sends Mosaic into
    # a pathological relayout (observed: compile hang on v5e)
    if D % 128 != 0:
        return False
    return Sq * (H // KV) <= 2048  # q block must sit in VMEM


def decode_attention(q, k_cache, v_cache, offset, scale=None,
                     interpret=None):
    """q [B,Sq,H,D] against caches [B,KV,M,D] (head-major: each head's
    [M,D] plane is contiguous, the Mosaic-tileable layout); cache
    positions <= offset+row are attended. offset may be traced, and may
    be a PER-ROW vector [B] (ragged batches: each row's frontier clamps
    its own DMA + mask independently)."""
    B, Sq, H, D = q.shape
    KV, M = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = not is_tpu_platform()
    block_kv = _pick_block(M, prefer=_BLOCKS)
    nkv = M // block_kv
    q5 = q.reshape(B, Sq, KV, G, D)
    lengths = jnp.broadcast_to(jnp.asarray(offset, jnp.int32).reshape(-1),
                               (B,))

    def kv_index(b, h, j, ln):
        return (b, h, jnp.minimum(j, (ln[b] + Sq - 1) // block_kv), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, KV, nkv),
        in_specs=[
            pl.BlockSpec((1, Sq, 1, G, D), lambda b, h, j, ln:
                         (b, 0, h, 0, 0)),
            pl.BlockSpec((1, 1, block_kv, D), kv_index),
            pl.BlockSpec((1, 1, block_kv, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, Sq, 1, G, D),
                               lambda b, h, j, ln: (b, 0, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Sq * G, 128), jnp.float32),
            pltpu.VMEM((Sq * G, 128), jnp.float32),
            pltpu.VMEM((Sq * G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        partial(_kernel, scale=scale, block_kv=block_kv, nkv=nkv, Sq=Sq,
                G=G),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, KV, G, D), q.dtype),
        interpret=interpret,
        **_compiler_params(2, interpret),
    )(lengths, q5, k_cache, v_cache)
    return out.reshape(B, Sq, H, D)


# ---------------------------------------------------------------------------
# Paged (block-table) KV cache attention
# ---------------------------------------------------------------------------
def _paged_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s,
                  acc_s, *, scale, page, npages, Sq, G):
    j = pl.program_id(2)
    off = len_ref[pl.program_id(0)]
    j_last = (off + Sq - 1) // page

    @pl.when(j == 0)
    def _():
        m_s[...] = jnp.full_like(m_s, _NEG)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(j <= j_last)
    def _():
        qb = q_ref[0, :, 0, :, :].reshape(Sq * G, -1)      # [Sq*G, D]
        kb = k_ref[0, 0]                                   # [page, D]
        vb = v_ref[0, 0]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        rows = lax.broadcasted_iota(jnp.int32, (Sq * G, page), 0) // G
        cols = j * page + lax.broadcasted_iota(
            jnp.int32, (Sq * G, page), 1)
        keep = cols <= off + rows
        s = jnp.where(keep, s, _NEG)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_s[:, :1] = l_s[:, :1] * corr + jnp.sum(p, -1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:, :1] = m_new

    @pl.when(j == npages - 1)
    def _():
        l = jnp.maximum(l_s[:, :1], 1e-30)
        o_ref[0, :, 0, :, :] = (acc_s[...] / l).reshape(
            Sq, G, -1).astype(o_ref.dtype)


def paged_supported(q_shape, pool_shape) -> bool:
    if pltpu is None:
        return False
    B, Sq, H, D = q_shape
    P, KV, page = pool_shape[0], pool_shape[1], pool_shape[2]
    if H % KV or D % 128 != 0:
        return False
    if page % 8 or page < 8:  # sublane-tileable page
        return False
    return Sq * (H // KV) <= 2048


def paged_decode_attention(q, k_pool, v_pool, block_tables, lengths,
                           scale=None, interpret=None):
    """Block-table KV attention (the TPU redesign of the reference's
    paged cache kernel: phi/kernels/fusion/gpu/
    block_multi_head_attention_kernel.cu + block_attn.h — there, CUDA
    threads chase the block table; here the BLOCKSPEC INDEX MAP does:
    the physical page id is gathered from a scalar-prefetched table, so
    the DMA engine fetches exactly the pages a row owns and never
    touches pages past its frontier).

    q            [B, Sq, H, D]  rows at absolute positions
                                lengths[b]..lengths[b]+Sq-1
    k/v_pool     [P, KV, page, D]  shared physical page pool, head-major
                                pages (each [page, D] plane contiguous)
    block_tables [B, npages]    logical->physical page map per row
    lengths      [B]            tokens already in cache per row (ragged)
    """
    B, Sq, H, D = q.shape
    P, KV, page = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    npages = block_tables.shape[1]
    G = H // KV
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = not is_tpu_platform()
    q5 = q.reshape(B, Sq, KV, G, D)
    lengths = jnp.asarray(lengths, jnp.int32).reshape(B)
    tbl = jnp.asarray(block_tables, jnp.int32).reshape(B * npages)

    def pool_index(b, h, j, ln, tb):
        jc = jnp.minimum(j, (ln[b] + Sq - 1) // page)
        return (tb[b * npages + jc], h, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, npages),
        in_specs=[
            pl.BlockSpec((1, Sq, 1, G, D), lambda b, h, j, ln, tb:
                         (b, 0, h, 0, 0)),
            pl.BlockSpec((1, 1, page, D), pool_index),
            pl.BlockSpec((1, 1, page, D), pool_index),
        ],
        out_specs=pl.BlockSpec((1, Sq, 1, G, D),
                               lambda b, h, j, ln, tb: (b, 0, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Sq * G, 128), jnp.float32),
            pltpu.VMEM((Sq * G, 128), jnp.float32),
            pltpu.VMEM((Sq * G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        partial(_paged_kernel, scale=scale, page=page, npages=npages,
                Sq=Sq, G=G),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, KV, G, D), q.dtype),
        interpret=interpret,
        **_compiler_params(2, interpret),
    )(lengths, tbl, q5, k_pool, v_pool)
    return out.reshape(B, Sq, H, D)


def paged_attention_dense(q, k_pool, v_pool, block_tables, lengths):
    """XLA reference/fallback: gather the pages into a contiguous view,
    then run the (ragged-aware) dense cache attention."""
    B, Sq, H, D = q.shape
    page = k_pool.shape[2]
    npages = block_tables.shape[1]
    # [B, npages, KV, page, D] -> [B, KV, npages*page, D]
    def gather(pool):
        g = pool[block_tables]                       # [B, npages, KV, page, D]
        g = jnp.swapaxes(g, 1, 2)                     # [B, KV, npages, page, D]
        return g.reshape(B, pool.shape[1], npages * page, D)

    return _dense_ragged(q, gather(k_pool), gather(v_pool), lengths)


def _dense_ragged(q, k_cache, v_cache, lengths):
    """Dense cache attention with per-row offsets (ragged).

    GQA never copies K/V per query head: q reshapes to [B, KV, rep, S,
    D] (query head h reads kv head h // rep) and the einsums broadcast
    the shared kv plane over the rep dim."""
    B, S, H, D = q.shape
    KV, M = k_cache.shape[1], k_cache.shape[2]
    rep = H // KV
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)          # [B, H, S, D]
    qf = qf.reshape(B, KV, rep, S, D)
    kf = k_cache.astype(jnp.float32)                        # [B, KV, M, D]
    vf = v_cache.astype(jnp.float32)
    scores = jnp.einsum("bkrsd,bkmd->bkrsm", qf, kf) / np.sqrt(D)
    off = jnp.asarray(lengths, jnp.int32).reshape(B)
    q_pos = off[:, None] + jnp.arange(S)[None, :]          # [B, S]
    keep = jnp.arange(M)[None, None, :] <= q_pos[:, :, None]
    scores = jnp.where(keep[:, None, None], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkrsm,bkmd->bkrsd", probs, vf)
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2).astype(q.dtype)
