"""Kernel autotuning: measured block-size selection with a persistent
algorithm cache.

(reference: paddle/phi/kernels/autotune/cache.h AlgorithmsCache +
switch_autotune.cc AutoTuneStatus — exhaustive-search cuDNN algo
selection keyed by shape/dtype, cached in memory for the process; here
additionally persisted to disk so later processes skip the search.)

TPU-native: the tunable is the Pallas BlockSpec tiling (block_q,
block_kv) of the flash kernels. Tuning runs EAGER side-benchmarks with
synthetic inputs — legal even while an outer jit is tracing, since
block sizes are trace-time Python values. Under the axon tunnel,
``block_until_ready`` does not wait, so measurements force a host
transfer (see .claude/skills/verify/SKILL.md).

Off by default (tuning compiles each candidate once — seconds of
one-time cost per new shape); enable with
``paddle.set_flags({"FLAGS_use_autotune": True})``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

__all__ = ["AlgoCache", "get_cache", "autotune"]


class AlgoCache:
    """In-memory + on-disk map: key string -> chosen config."""

    def __init__(self, path: Optional[str] = None):
        self._mem: Dict[str, list] = {}
        self._path = path
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._mem.update(json.load(f))
            except Exception:
                pass

    def get(self, key: str):
        v = self._mem.get(key)
        return tuple(v) if isinstance(v, list) else v

    def put(self, key: str, value) -> None:
        self._mem[key] = list(value) if isinstance(value, tuple) else value
        if self._path:
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                with open(self._path, "w") as f:
                    json.dump(self._mem, f)
            except Exception:
                pass

    def size(self) -> int:
        return len(self._mem)


_cache: Optional[AlgoCache] = None


def _default_path() -> Optional[str]:
    p = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")
    if p == "":
        return None  # explicit opt-out of persistence
    return p or os.path.join(os.path.expanduser("~"), ".cache",
                             "paddle_tpu", "autotune.json")


def get_cache() -> AlgoCache:
    global _cache
    if _cache is None:
        _cache = AlgoCache(_default_path())
    return _cache


def autotune(key: str, candidates: Sequence, measure: Callable,
             cache: Optional[AlgoCache] = None):
    """Return the cached choice for ``key`` or measure all candidates
    (``measure(candidate) -> seconds``; inf/exception = infeasible) and
    cache the argmin."""
    cache = cache or get_cache()
    hit = cache.get(key)
    if hit is not None:
        return hit
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            t = measure(cand)
        except Exception:
            continue
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        raise RuntimeError(f"autotune: no feasible candidate for {key}")
    cache.put(key, best)
    return best


def measure_flash_blocks(q_shape, kv_len: int, dtype, causal: bool,
                         reps: int = 5) -> Callable:
    """Measurement closure for the flash forward kernel: compile the
    candidate blocks and time ``reps`` runs at the REAL (possibly
    rectangular) problem shape, forcing a host transfer (axon's
    block_until_ready is a lie)."""
    import numpy as np
    import jax.numpy as jnp

    from .flash_attention import _pallas_fa

    B, S, H, D = q_shape
    r = np.random.RandomState(0)
    q3 = jnp.asarray(r.randn(B * H, S, D), dtype)
    k3 = jnp.asarray(r.randn(B * H, kv_len, D), dtype)
    v3 = jnp.asarray(r.randn(B * H, kv_len, D), dtype)
    scale = 1.0 / np.sqrt(D)

    def measure(cand) -> float:
        bq, bkv = cand
        if S % bq or kv_len % bkv:
            return float("inf")
        out = _pallas_fa(q3, k3, v3, None, None, H, causal, scale, bq,
                         bkv, False)[0]
        float(out.astype(jnp.float32).sum())  # compile + settle
        t0 = time.perf_counter()
        for _ in range(reps):
            out = _pallas_fa(q3, k3, v3, None, None, H, causal, scale,
                             bq, bkv, False)[0]
        float(out.astype(jnp.float32).sum())
        return (time.perf_counter() - t0) / reps

    return measure
