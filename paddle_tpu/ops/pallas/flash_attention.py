"""Pallas TPU flash attention (forward + backward kernels).

TPU-native replacement for the reference's dynloaded flashattn-v2 CUDA
library (reference: phi/kernels/gpu/flash_attn_kernel.cu,
flash_attn_grad_kernel.cu, backends/dynload/flashattn.h, python surface
nn/functional/flash_attention.py:147).

Design: classic flash — the q block lives in VMEM, k/v stream through
VMEM blocks, online-softmax statistics (m, l) carried through a
fori_loop so attention probabilities never hit HBM. The causal variant
skips k/v blocks entirely above the diagonal (the loop's upper bound is
a function of the q-block index), halving FLOPs.

Backward (FlashAttention-2 recurrence, the capability of the
reference's flash_attn_grad_kernel.cu): the forward additionally emits
the per-row logsumexp L; backward recomputes P = exp(S - L) blockwise in
VMEM and runs TWO kernels — a dq kernel gridded over q blocks and a
dk/dv kernel gridded over kv blocks (TPU has no atomics, so each output
gets its own reduction loop). Residual memory is O(S) per head
(L + delta), never O(S²).

Layout [B, S, H, D] (the paddle flash_attention layout). Grid:
(B*H, S/block); f32 accumulation; MXU-shaped tiles (128 lanes).
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from . import is_tpu_platform, pick_block as _pick_block

__all__ = ["flash_attention_fwd"]

_NEG = -1e30


def _causal_mask(qi, j, block_q, block_kv):
    rows = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)
    cols = j * block_kv + lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    return rows >= cols


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_q,
            block_kv, seq_kv):
    qb = q_ref[0].astype(jnp.float32) * scale            # [bq, D]
    qi = pl.program_id(1)
    D = qb.shape[-1]
    nkv = seq_kv // block_kv

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            keep = _causal_mask(qi, j, block_q, block_kv)
            s = jnp.where(keep, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    if causal:
        # blocks strictly above the diagonal contribute nothing — skip
        upper = jnp.minimum(
            (qi * block_q + block_q + block_kv - 1) // block_kv, nkv)
    else:
        upper = nkv
    m, l, acc = lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0, :] = (m + jnp.log(l))[:, 0]


def _pallas_fa(q3, k3, v3, causal, scale, block_q, block_kv, interpret):
    BH, S, D = q3.shape
    Skv = k3.shape[1]
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    return pl.pallas_call(
        partial(_kernel, scale=scale, causal=causal, block_q=block_q,
                block_kv=block_kv, seq_kv=Skv),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0), **kw),
            pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0), **kw),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i), **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


# ---------------------------------------------------------------------------
# Backward kernels (reference capability: flash_attn_grad_kernel.cu)
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, *,
               scale, causal, block_q, block_kv, seq_kv):
    qb = q_ref[0].astype(jnp.float32) * scale            # [bq, D]
    dob = do_ref[0].astype(jnp.float32)                  # [bq, D]
    lse = lse_ref[0, 0, :].astype(jnp.float32)[:, None]   # [bq, 1]
    delta = dl_ref[0, 0, :].astype(jnp.float32)[:, None]  # [bq, 1]
    qi = pl.program_id(1)
    D = qb.shape[-1]
    nkv = seq_kv // block_kv

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            keep = _causal_mask(qi, j, block_q, block_kv)
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)                             # [bq, bkv]
        if causal:
            p = jnp.where(keep, p, 0.0)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + lax.dot_general(ds, kb, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    if causal:
        upper = jnp.minimum(
            (qi * block_q + block_q + block_kv - 1) // block_kv, nkv)
    else:
        upper = nkv
    dq = lax.fori_loop(0, upper, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref,
                dv_ref, *, scale, causal, block_q, block_kv, seq_q):
    kb = k_ref[0].astype(jnp.float32)                    # [bkv, D]
    vb = v_ref[0].astype(jnp.float32)
    ki = pl.program_id(1)
    D = kb.shape[-1]
    nq = seq_q // block_q

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32) * scale
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)].astype(
            jnp.float32)[:, None]
        delta = dl_ref[0, 0, pl.ds(i * block_q, block_q)].astype(
            jnp.float32)[:, None]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            keep = _causal_mask(i, ki, block_q, block_kv)
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(keep, p, 0.0)
        dv = dv + lax.dot_general(p, dob, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + lax.dot_general(ds, qb, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    # causal: q blocks strictly before this kv block see none of it
    lower = (ki * block_kv) // block_q if causal else 0
    z = jnp.zeros((block_kv, D), jnp.float32)
    dk, dv = lax.fori_loop(lower, nq, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pallas_fa_bwd(q3, k3, v3, do3, lse, delta, causal, scale, block_q,
                   block_kv, interpret):
    BH, S, D = q3.shape
    Skv = k3.shape[1]
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    dq = pl.pallas_call(
        partial(_dq_kernel, scale=scale, causal=causal, block_q=block_q,
                block_kv=block_kv, seq_kv=Skv),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0), **kw),
            pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0), **kw),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i), **kw),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i), **kw),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0),
                               **kw),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    dk, dv = pl.pallas_call(
        partial(_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
                block_kv=block_kv, seq_q=S),
        grid=(BH, Skv // block_kv),
        in_specs=[
            pl.BlockSpec((1, S, D), lambda b, j: (b, 0, 0), **kw),
            pl.BlockSpec((1, block_kv, D), lambda b, j: (b, j, 0), **kw),
            pl.BlockSpec((1, block_kv, D), lambda b, j: (b, j, 0), **kw),
            pl.BlockSpec((1, S, D), lambda b, j: (b, 0, 0), **kw),
            pl.BlockSpec((1, 1, S), lambda b, j: (b, 0, 0), **kw),
            pl.BlockSpec((1, 1, S), lambda b, j: (b, 0, 0), **kw),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, D), lambda b, j: (b, j, 0), **kw),
            pl.BlockSpec((1, block_kv, D), lambda b, j: (b, j, 0), **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Skv, D), k3.dtype),
            jax.ShapeDtypeStruct((BH, Skv, D), v3.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


def _supported(q, k) -> bool:
    B, S, H, D = q.shape
    return k.shape[1] == S and _pick_block(S) > 0


def _interpret_default() -> bool:
    return not is_tpu_platform()


def _to3(x):
    B, S, H, D = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(B * H, S, D)


def _from3(x3, B, H):
    BH, S, D = x3.shape
    return jnp.swapaxes(x3.reshape(B, H, S, D), 1, 2)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_fwd(q, k, v, causal=False, scale=None,
                        interpret=None):
    """[B, S, H, D] → [B, S, H, D]; raises ValueError when the shape
    needs the XLA fallback (caller catches)."""
    out, _ = _fa_fwd(q, k, v, causal, scale, interpret)
    return out


def _fa_fwd(q, k, v, causal, scale, interpret):
    if not _supported(q, k):
        raise ValueError("flash pallas kernel: unsupported shape "
                         f"{q.shape}/{k.shape}")
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()
    block_q = _pick_block(S)
    block_kv = _pick_block(k.shape[1])
    o3, lse = _pallas_fa(_to3(q), _to3(k), _to3(v), causal, scale, block_q,
                         block_kv, interpret)
    out = _from3(o3, B, H)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, interpret, res, g):
    q, k, v, out, lse = res
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()
    q3, k3, v3 = _to3(q), _to3(k), _to3(v)
    do3, o3 = _to3(g), _to3(out)
    # delta_i = rowsum(dO ∘ O): O(S) per head, fused by XLA
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]
    block_q = _pick_block(S)
    block_kv = _pick_block(k.shape[1])
    dq3, dk3, dv3 = _pallas_fa_bwd(q3, k3, v3, do3, lse, delta, causal,
                                   scale, block_q, block_kv, interpret)
    return (_from3(dq3, B, H), _from3(dk3, B, H), _from3(dv3, B, H))


flash_attention_fwd.defvjp(lambda q, k, v, causal, scale, interpret:
                           _fa_fwd(q, k, v, causal, scale, interpret),
                           _fa_bwd)
