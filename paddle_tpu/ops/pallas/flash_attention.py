"""Pallas TPU flash attention (forward kernel + recompute backward).

TPU-native replacement for the reference's dynloaded flashattn-v2 CUDA
library (reference: phi/kernels/gpu/flash_attn_kernel.cu,
backends/dynload/flashattn.h, python surface
nn/functional/flash_attention.py:147).

Design: classic flash — the q block lives in VMEM, k/v stream through
VMEM blocks, online-softmax statistics (m, l) carried through a
fori_loop so attention probabilities never hit HBM. The causal variant
skips k/v blocks entirely above the diagonal (the loop's upper bound is
a function of the q-block index), halving FLOPs. Backward recomputes
through the XLA softmax-attention VJP under jax.checkpoint semantics —
residuals are just (q, k, v), preserving flash's O(S) memory.

Layout [B, S, H, D] (the paddle flash_attention layout). Grid:
(B*H, S/block_q); f32 accumulation; MXU-shaped tiles (128 lanes).
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from . import is_tpu_platform, pick_block as _pick_block

__all__ = ["flash_attention_fwd"]

_NEG = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q,
            block_kv, seq_kv):
    qb = q_ref[0].astype(jnp.float32) * scale            # [bq, D]
    qi = pl.program_id(1)
    D = qb.shape[-1]
    nkv = seq_kv // block_kv

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = j * block_kv + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = rows >= cols
            s = jnp.where(keep, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    if causal:
        # blocks strictly above the diagonal contribute nothing — skip
        upper = jnp.minimum(
            (qi * block_q + block_q + block_kv - 1) // block_kv, nkv)
    else:
        upper = nkv
    m, l, acc = lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pallas_fa(q3, k3, v3, causal, scale, block_q, block_kv, interpret):
    BH, S, D = q3.shape
    Skv = k3.shape[1]
    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    return pl.pallas_call(
        partial(_kernel, scale=scale, causal=causal, block_q=block_q,
                block_kv=block_kv, seq_kv=Skv),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0), **kw),
            pl.BlockSpec((1, Skv, D), lambda b, i: (b, 0, 0), **kw),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0),
                               **kw),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        interpret=interpret,
    )(q3, k3, v3)


def _supported(q, k) -> bool:
    B, S, H, D = q.shape
    return k.shape[1] == S and _pick_block(S) > 0


def _interpret_default() -> bool:
    return not is_tpu_platform()


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_fwd(q, k, v, causal=False, scale=None,
                        interpret=None):
    """[B, S, H, D] → [B, S, H, D]; raises ValueError when the shape
    needs the XLA fallback (caller catches)."""
    out, _ = _fa_fwd(q, k, v, causal, scale, interpret)
    return out


def _fa_fwd(q, k, v, causal, scale, interpret):
    if not _supported(q, k):
        raise ValueError("flash pallas kernel: unsupported shape "
                         f"{q.shape}/{k.shape}")
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()
    block_q = _pick_block(S)
    block_kv = _pick_block(k.shape[1])
    to3 = lambda x: jnp.swapaxes(x, 1, 2).reshape(B * H, x.shape[1], D)
    o3 = _pallas_fa(to3(q), to3(k), to3(v), causal, scale, block_q,
                    block_kv, interpret)
    out = jnp.swapaxes(o3.reshape(B, H, S, D), 1, 2)
    return out, (q, k, v)


def _fa_bwd(causal, scale, interpret, res, g):
    # recompute-based backward: O(S) residual memory, XLA fuses the
    # attention VJP (flash backward Pallas kernel is a future upgrade)
    q, k, v = res
    from ..nn_ops import scaled_dot_product_attention as _sdpa

    def ref(q_, k_, v_):
        return _sdpa.raw(q_, k_, v_, attn_mask=None, dropout_p=0.0,
                         is_causal=causal, scale=scale)

    _, vjp_fn = jax.vjp(ref, q, k, v)
    return vjp_fn(g)


flash_attention_fwd.defvjp(lambda q, k, v, causal, scale, interpret:
                           _fa_fwd(q, k, v, causal, scale, interpret),
                           _fa_bwd)
