"""Pallas TPU flash attention (forward + backward kernels).

TPU-native replacement for the reference's dynloaded flashattn-v2 CUDA
library (reference: phi/kernels/gpu/flash_attn_kernel.cu,
flash_attn_grad_kernel.cu, backends/dynload/flashattn.h, python surface
nn/functional/flash_attention.py:147).

Design: K/V STREAM through VMEM as the innermost *grid* dimension (no
full-KV VMEM pin), with the online-softmax statistics (m, l) and the
output accumulator carried across grid steps in VMEM scratch — TPU grid
iteration is sequential over the last axis, which is exactly the
guarantee the recurrence needs. Causal blocks above the diagonal are
skipped two ways: the compute is guarded by ``pl.when`` and the
BlockSpec index map clamps to the last valid block so Pallas re-uses
the resident block instead of issuing a DMA.

Rectangular attention (seq_q != seq_kv) follows the flash-attn
convention: the q rows are the LAST seq_q rows of the seq_kv-length
sequence (q_offset = seq_kv - seq_q) under ``causal``.

Varlen/packed sequences are expressed with integer segment ids
(q_segment_ids [B, Sq], kv_segment_ids [B, Skv]): position pairs in
different segments never attend. ``flash_attn_unpadded`` builds these
from cu_seqlens (see ops/attention.py).

Backward (FlashAttention-2 recurrence, the capability of the
reference's flash_attn_grad_kernel.cu): the forward additionally emits
the per-row logsumexp L; backward recomputes P = exp(S - L) blockwise in
VMEM and runs TWO kernels — a dq kernel (grid over q blocks, kv
streaming innermost) and a dk/dv kernel (grid over kv blocks, q
streaming innermost); TPU has no atomics, so each output owns its
reduction. Residual memory is O(S) per head (L + delta), never O(S²).

Layout [B, S, H, D] (the paddle flash_attention layout). Grid:
(B*H, blocks, blocks); f32 accumulation; MXU-shaped tiles (128 lanes).
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from . import (_BLOCKS_LARGE as _BLOCKS, compiler_params as
               _compiler_params, is_tpu_platform, pick_block as _pick_block)

__all__ = ["flash_attention_fwd"]

_NEG = -1e30


def _mask(qi, j, block_q, block_kv, q_off, causal, qseg, kseg):
    """[block_q, block_kv] keep-mask (True = attend) or None if nothing
    is masked. qseg/kseg are VMEM blocks or None."""
    keep = None
    if causal:
        rows = q_off + qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        cols = j * block_kv + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        keep = rows >= cols
    if qseg is not None:
        same = qseg[0, 0][:, None] == kseg[0, 0][None, :]
        keep = same if keep is None else (keep & same)
    return keep


def _last_kv_block(qi, block_q, block_kv, q_off, causal, nkv):
    """Index of the last kv block any row of q-block ``qi`` attends to."""
    if not causal:
        return nkv - 1
    return jnp.minimum(
        (q_off + (qi + 1) * block_q - 1) // block_kv, nkv - 1)


def _first_q_block(ki, block_q, block_kv, q_off, causal, nq):
    """Index of the first q block that sees kv block ``ki`` (causal)."""
    if not causal:
        return 0
    return jnp.clip((ki * block_kv - q_off) // block_q, 0, nq - 1)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, block_q,
                block_kv, q_off, nkv, has_seg):
    if has_seg:
        qseg_ref, kseg_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
    else:
        o_ref, lse_ref, m_s, l_s, acc_s = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    j = pl.program_id(2)
    j_last = _last_kv_block(qi, block_q, block_kv, q_off, causal, nkv)

    @pl.when(j == 0)
    def _():
        m_s[...] = jnp.full_like(m_s, _NEG)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(j <= j_last)
    def _():
        # matmuls run in the INPUT dtype (bf16 = native MXU mode; f32
        # inputs stay accurate) with f32 accumulation
        qb = q_ref[0]                                    # [bq, D]
        kb = k_ref[0]                                    # [bkv, D]
        vb = v_ref[0]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        keep = _mask(qi, j, block_q, block_kv, q_off, causal,
                     qseg_ref, kseg_ref)
        if keep is not None:
            s = jnp.where(keep, s, _NEG)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_s[:, :1] = l_s[:, :1] * corr + jnp.sum(p, -1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:, :1] = m_new

    @pl.when(j == nkv - 1)
    def _():
        l = jnp.maximum(l_s[:, :1], 1e-30)
        o_ref[0] = (acc_s[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m_s[:, :1] + jnp.log(l))[:, 0]


def _seg_specs(H, block_q, block_kv, kv_index, kw):
    """BlockSpecs for segment-id arrays reshaped to [B, 1, S] (3-D so
    the Mosaic last-two-dims tiling rule is satisfiable for B > 1);
    the BH grid axis maps to batch via // H."""
    qs = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b // H, 0, i),
                      **kw)
    ks = pl.BlockSpec((1, 1, block_kv),
                      lambda b, i, j: (b // H, 0, kv_index(b, i, j)), **kw)
    return qs, ks


def _pallas_fa(q3, k3, v3, qseg, kseg, H, causal, scale, block_q, block_kv,
               interpret):
    BH, Sq, D = q3.shape
    Skv = k3.shape[1]
    q_off = Skv - Sq
    nq, nkv = Sq // block_q, Skv // block_kv
    kw = {} if _VMEM is None else {"memory_space": _VMEM}

    def kv_index(b, i, j):
        # clamp past the causal frontier: re-use the resident block, no DMA
        return jnp.minimum(
            j, _last_kv_block(i, block_q, block_kv, q_off, causal, nkv))

    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), **kw),
        pl.BlockSpec((1, block_kv, D),
                     lambda b, i, j: (b, kv_index(b, i, j), 0), **kw),
        pl.BlockSpec((1, block_kv, D),
                     lambda b, i, j: (b, kv_index(b, i, j), 0), **kw),
    ]
    args = [q3, k3, v3]
    if qseg is not None:
        qs, ks = _seg_specs(H, block_q, block_kv, kv_index, kw)
        in_specs += [qs, ks]
        args += [qseg, kseg]
    kernel = partial(_fwd_kernel, scale=scale, causal=causal,
                     block_q=block_q, block_kv=block_kv, q_off=q_off,
                     nkv=nkv, has_seg=qseg is not None)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nkv),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), **kw),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
        **_compiler_params(2, interpret),
    )(*args)


# ---------------------------------------------------------------------------
# Backward kernels (reference capability: flash_attn_grad_kernel.cu)
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *refs, scale,
               causal, block_q, block_kv, q_off, nkv, has_seg):
    if has_seg:
        qseg_ref, kseg_ref, dq_ref, dq_s = refs
    else:
        dq_ref, dq_s = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    j = pl.program_id(2)
    j_last = _last_kv_block(qi, block_q, block_kv, q_off, causal, nkv)

    @pl.when(j == 0)
    def _():
        dq_s[...] = jnp.zeros_like(dq_s)

    @pl.when(j <= j_last)
    def _():
        qb = q_ref[0]
        dob = do_ref[0]
        lse = lse_ref[0, 0, :][:, None]
        delta = dl_ref[0, 0, :][:, None]
        kb = k_ref[0]
        vb = v_ref[0]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        keep = _mask(qi, j, block_q, block_kv, q_off, causal,
                     qseg_ref, kseg_ref)
        if keep is not None:
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_s[...] += lax.dot_general(ds.astype(kb.dtype), kb,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(j == nkv - 1)
    def _():
        dq_ref[0] = (dq_s[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *refs, scale,
                causal, block_q, block_kv, q_off, nq, has_seg):
    if has_seg:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_s, dv_s = refs
    else:
        dk_ref, dv_ref, dk_s, dv_s = refs
        qseg_ref = kseg_ref = None
    ki = pl.program_id(1)
    i = pl.program_id(2)
    i_first = _first_q_block(ki, block_q, block_kv, q_off, causal, nq)

    @pl.when(i == 0)
    def _():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    @pl.when(i >= i_first)
    def _():
        kb = k_ref[0]
        vb = v_ref[0]
        qb = q_ref[0]
        dob = do_ref[0]
        lse = lse_ref[0, 0, :][:, None]
        delta = dl_ref[0, 0, :][:, None]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        keep = _mask(i, ki, block_q, block_kv, q_off, causal,
                     qseg_ref, kseg_ref)
        if keep is not None:
            s = jnp.where(keep, s, _NEG)
        p = jnp.exp(s - lse)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dv_s[...] += lax.dot_general(p.astype(dob.dtype), dob,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - delta))
        dk_s[...] += lax.dot_general(ds.astype(qb.dtype), qb,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = (dk_s[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _pallas_fa_bwd(q3, k3, v3, do3, lse, delta, qseg, kseg, H, causal,
                   scale, block_q, block_kv, interpret):
    BH, Sq, D = q3.shape
    Skv = k3.shape[1]
    q_off = Skv - Sq
    nq, nkv = Sq // block_q, Skv // block_kv
    kw = {"memory_space": _VMEM}
    scratch = [pltpu.VMEM((block_q, D), jnp.float32)]

    def kv_index(b, i, j):
        return jnp.minimum(
            j, _last_kv_block(i, block_q, block_kv, q_off, causal, nkv))

    dq_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), **kw),
        pl.BlockSpec((1, block_kv, D),
                     lambda b, i, j: (b, kv_index(b, i, j), 0), **kw),
        pl.BlockSpec((1, block_kv, D),
                     lambda b, i, j: (b, kv_index(b, i, j), 0), **kw),
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), **kw),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), **kw),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), **kw),
    ]
    dq_args = [q3, k3, v3, do3, lse, delta]
    if qseg is not None:
        qs, ks = _seg_specs(H, block_q, block_kv, kv_index, kw)
        dq_specs += [qs, ks]
        dq_args += [qseg, kseg]
    dq_kernel = partial(_dq_kernel, scale=scale, causal=causal,
                        block_q=block_q, block_kv=block_kv, q_off=q_off,
                        nkv=nkv, has_seg=qseg is not None)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, nq, nkv),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                               **kw),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), q3.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        **_compiler_params(2, interpret),
    )(*dq_args)

    def q_index(b, j, i):
        # clamp before the causal frontier: skip the DMA for q blocks
        # that cannot see this kv block
        return jnp.maximum(
            i, _first_q_block(j, block_q, block_kv, q_off, causal, nq))

    dkv_specs = [
        pl.BlockSpec((1, block_q, D),
                     lambda b, j, i: (b, q_index(b, j, i), 0), **kw),
        pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0), **kw),
        pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0), **kw),
        pl.BlockSpec((1, block_q, D),
                     lambda b, j, i: (b, q_index(b, j, i), 0), **kw),
        pl.BlockSpec((1, 1, block_q),
                     lambda b, j, i: (b, 0, q_index(b, j, i)), **kw),
        pl.BlockSpec((1, 1, block_q),
                     lambda b, j, i: (b, 0, q_index(b, j, i)), **kw),
    ]
    dkv_args = [q3, k3, v3, do3, lse, delta]
    if qseg is not None:
        qs = pl.BlockSpec(
            (1, 1, block_q),
            lambda b, j, i: (b // H, 0, q_index(b, j, i)), **kw)
        ks = pl.BlockSpec((1, 1, block_kv),
                          lambda b, j, i: (b // H, 0, j), **kw)
        dkv_specs += [qs, ks]
        dkv_args += [qseg, kseg]
    dkv_kernel = partial(_dkv_kernel, scale=scale, causal=causal,
                         block_q=block_q, block_kv=block_kv, q_off=q_off,
                         nq=nq, has_seg=qseg is not None)
    dkv_scratch = [pltpu.VMEM((block_kv, D), jnp.float32),
                   pltpu.VMEM((block_kv, D), jnp.float32)]
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, nkv, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0), **kw),
            pl.BlockSpec((1, block_kv, D), lambda b, j, i: (b, j, 0), **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Skv, D), k3.dtype),
            jax.ShapeDtypeStruct((BH, Skv, D), v3.dtype),
        ],
        scratch_shapes=dkv_scratch,
        interpret=interpret,
        **_compiler_params(2, interpret),
    )(*dkv_args)
    return dq, dk, dv


def _supported(q, k) -> bool:
    if pltpu is None:  # no TPU pallas backend: scratch/VMEM unavailable
        return False
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    if _pick_block(Sq) <= 0 or _pick_block(Skv) <= 0:
        return False
    # D must fill whole 128-wide VPU lanes ON REAL TPU: sub-lane head
    # dims were observed to hang the Mosaic compiler on v5e (same gate
    # as rms_norm/decode_attention); interpret mode has no such limit
    if not _interpret_default() and D % 128 != 0:
        return False
    # rectangular causal convention needs q to be a suffix of the kv span
    return Skv >= Sq


def _interpret_default() -> bool:
    return not is_tpu_platform()


def _to3(x):
    B, S, H, D = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(B * H, S, D)


def _from3(x3, B, H):
    BH, S, D = x3.shape
    return jnp.swapaxes(x3.reshape(B, H, S, D), 1, 2)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_fwd(q, k, v, causal=False, scale=None, interpret=None,
                        q_segment_ids=None, kv_segment_ids=None):
    """[B, S, H, D] → [B, S, H, D]; raises ValueError when the shape
    needs the XLA fallback (caller catches). Optional int32 segment ids
    [B, Sq]/[B, Skv] restrict attention to equal segments (varlen)."""
    out, _ = _fa_fwd(q, k, v, causal, scale, interpret, q_segment_ids,
                     kv_segment_ids)
    return out


def _prep(q, k, causal, scale, interpret, qseg, kseg):
    B, Sq, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()
    if (qseg is None) != (kseg is None):
        raise ValueError("flash: q/kv segment ids must be given together")
    if qseg is not None:
        # [B, S] -> [B, 1, S] (see _seg_specs)
        qseg = jnp.asarray(qseg, jnp.int32)[:, None, :]
        kseg = jnp.asarray(kseg, jnp.int32)[:, None, :]
    # 512-blocks measured fastest on v5e at S=8192 (44.9 TF/s vs 9.7 at
    # 128); smaller sizes only when the sequence doesn't divide
    block_q = _pick_block(Sq, prefer=_BLOCKS)
    block_kv = _pick_block(k.shape[1], prefer=_BLOCKS)
    from ...core import flags as _flags

    if (_flags._get("use_autotune", False) and not interpret
            and qseg is None):
        # measured block selection, cached per shape/dtype (reference
        # AlgorithmsCache); runs eager side-benchmarks even while an
        # outer jit traces — block sizes are trace-time constants
        from .autotune import autotune, measure_flash_blocks

        B, Sq_, H, D = q.shape
        key = (f"flash:{B}x{Sq_}x{H}x{D}:{k.shape[1]}:{q.dtype}:"
               f"{bool(causal)}")
        cands = [(bq, bk) for bq in (512, 256, 128)
                 for bk in (512, 256, 128)
                 if Sq_ % bq == 0 and k.shape[1] % bk == 0]
        if len(cands) > 1:
            block_q, block_kv = autotune(
                key, cands,
                measure_flash_blocks(q.shape, k.shape[1], q.dtype,
                                     bool(causal)))
    return scale, interpret, qseg, kseg, block_q, block_kv


def _fa_fwd(q, k, v, causal, scale, interpret, qseg=None, kseg=None):
    if not _supported(q, k):
        raise ValueError("flash pallas kernel: unsupported shape "
                         f"{q.shape}/{k.shape}")
    B, Sq, H, D = q.shape
    scale, interpret, qseg3, kseg3, block_q, block_kv = _prep(
        q, k, causal, scale, interpret, qseg, kseg)
    o3, lse = _pallas_fa(_to3(q), _to3(k), _to3(v), qseg3, kseg3, H,
                         causal, scale, block_q, block_kv, interpret)
    out = _from3(o3, B, H)
    # residuals keep the RAW [B, S] ids — _fa_bwd re-runs _prep
    return out, (q, k, v, out, lse, qseg, kseg)


def _fa_bwd(causal, scale, interpret, res, g):
    q, k, v, out, lse, qseg, kseg = res
    B, Sq, H, D = q.shape
    scale, interpret, qseg, kseg, block_q, block_kv = _prep(
        q, k, causal, scale, interpret, qseg, kseg)
    q3, k3, v3 = _to3(q), _to3(k), _to3(v)
    do3, o3 = _to3(g), _to3(out)
    # delta_i = rowsum(dO ∘ O): O(S) per head, fused by XLA
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]
    dq3, dk3, dv3 = _pallas_fa_bwd(q3, k3, v3, do3, lse, delta, qseg, kseg,
                                   H, causal, scale, block_q, block_kv,
                                   interpret)
    return (_from3(dq3, B, H), _from3(dk3, B, H), _from3(dv3, B, H),
            None, None)


flash_attention_fwd.defvjp(
    lambda q, k, v, causal, scale, interpret, qseg=None, kseg=None:
    _fa_fwd(q, k, v, causal, scale, interpret, qseg, kseg),
    _fa_bwd)
