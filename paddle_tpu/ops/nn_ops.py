"""Neural-network operators.

(reference: python/paddle/nn/functional/*; phi kernels conv_kernel,
pool_kernel, layer_norm_kernel, rms_norm_kernel (gpu/rms_norm_kernel.cu),
flash_attn_kernel (gpu/flash_attn_kernel.cu), softmax_with_cross_entropy.)

All kernels lower to XLA ops that map onto the MXU (conv/matmul via
lax.conv_general_dilated / dot_general) or fuse on the VPU. Hot fused ops
(flash attention, rms_norm, rope) have Pallas TPU implementations in
paddle_tpu/ops/pallas/ selected via FLAGS_use_pallas_kernels on TPU.
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op
from ..core.dtype import convert_dtype

# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------


@def_op("relu")
def relu(x):
    return jax.nn.relu(x)


@def_op("relu6")
def relu6(x):
    return jax.nn.relu6(x)


@def_op("leaky_relu")
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@def_op("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@def_op("selu")
def selu(x):
    return jax.nn.selu(x)


@def_op("celu")
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


@def_op("gelu")
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@def_op("silu")
def silu(x):
    return jax.nn.silu(x)


def swish(x):
    return silu(x)


@def_op("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@def_op("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@def_op("hardsigmoid")
def hardsigmoid(x, slope=1.0 / 6, offset=0.5):
    return jnp.clip(x * slope + offset, 0.0, 1.0)


@def_op("hardswish")
def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@def_op("hardtanh")
def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@def_op("softplus")
def softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(x * beta > threshold, x, jax.nn.softplus(x * beta) / beta)


@def_op("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@def_op("tanhshrink")
def tanhshrink(x):
    return x - jnp.tanh(x)


@def_op("hardshrink")
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@def_op("softshrink")
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@def_op("prelu")
def prelu(x, weight):
    w = weight
    if w.size > 1 and x.ndim == 4:  # per-channel, NCHW
        w = w.reshape(1, -1, 1, 1)
    return jnp.where(x > 0, x, w * x)


@def_op("glu")
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@def_op("softmax")
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@def_op("log_softmax")
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@def_op("gumbel_softmax", differentiable=False)
def _gumbel_softmax(x, key, temperature=1.0, hard=False, axis=-1):
    g = jax.random.gumbel(key, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
        y = y_hard + y - lax.stop_gradient(y)
    return y


# ---------------------------------------------------------------------------
# Linear / matmul fused
# ---------------------------------------------------------------------------


@def_op("linear")
def linear(x, weight, bias=None):
    """x @ W (+ b); paddle weight layout [in_features, out_features]."""
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


@def_op("fused_gemm_epilogue")
def fused_gemm_epilogue(x, weight, bias, trans_x=False, trans_y=False,
                        activation="none"):
    """matmul+bias+act fused (reference: fused_gemm_epilogue via cuBLASLt;
    on TPU XLA fuses the epilogue into the MXU matmul automatically)."""
    if trans_x:
        x = jnp.swapaxes(x, -1, -2)
    if trans_y:
        weight = jnp.swapaxes(weight, -1, -2)
    out = jnp.matmul(x, weight) + bias
    if activation == "relu":
        out = jax.nn.relu(out)
    elif activation == "gelu":
        out = jax.nn.gelu(out)
    return out


# ---------------------------------------------------------------------------
# Convolutions / pooling
# ---------------------------------------------------------------------------


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@def_op("conv2d")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    stride = _norm_tuple(stride, 2)
    dilation = _norm_tuple(dilation, 2)
    if isinstance(padding, str):
        pad = padding.upper()
    elif isinstance(padding, int):
        pad = [(padding, padding), (padding, padding)]
    elif len(padding) == 2 and all(isinstance(p, int) for p in padding):
        pad = [(padding[0], padding[0]), (padding[1], padding[1])]
    else:
        pad = [tuple(p) for p in padding]
    dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=dn,
    )
    if bias is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + bias.reshape(bshape)
    return out


@def_op("conv1d")
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    stride = _norm_tuple(stride, 1)
    dilation = _norm_tuple(dilation, 1)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = padding if isinstance(padding, int) else padding[0]
        pad = [(p, p)]
    dn = ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "HIO", "NHC")
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, feature_group_count=groups, dimension_numbers=dn)
    if bias is not None:
        out = out + bias.reshape((1, -1, 1) if data_format == "NCL" else (1, 1, -1))
    return out


@def_op("conv2d_transpose")
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCHW"):
    stride = _norm_tuple(stride, 2)
    dilation = _norm_tuple(dilation, 2)
    p = _norm_tuple(padding, 2)
    opad = _norm_tuple(output_padding, 2)
    # paddle/conv-transpose semantics: insert (stride-1) zeros, flip kernel.
    kh = (weight.shape[2] - 1) * dilation[0] + 1
    kw = (weight.shape[3] - 1) * dilation[1] + 1
    pad = [(kh - 1 - p[0], kh - 1 - p[0] + opad[0]),
           (kw - 1 - p[1], kw - 1 - p[1] + opad[1])]
    w = jnp.flip(weight, axis=(2, 3))
    w = jnp.swapaxes(w, 0, 1)  # IOHW -> OIHW
    if groups > 1:
        ci = weight.shape[0]
        w = weight.reshape(groups, ci // groups, *weight.shape[1:])
        w = jnp.flip(w, axis=(3, 4))
        w = jnp.swapaxes(w, 1, 2).reshape(-1, ci // groups, *weight.shape[2:])
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad,
        lhs_dilation=stride, rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@def_op("max_pool2d")
def max_pool2d(x, kernel_size=2, stride=None, padding=0, ceil_mode=False,
               data_format="NCHW"):
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride if stride is not None else kernel_size, 2)
    p = _norm_tuple(padding, 2)
    if data_format == "NCHW":
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, init, lax.max, window, strides, pads)


@def_op("avg_pool2d")
def avg_pool2d(x, kernel_size=2, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCHW"):
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride if stride is not None else kernel_size, 2)
    p = _norm_tuple(padding, 2)
    if data_format == "NCHW":
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if exclusive and (p[0] or p[1]):
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return summed / counts
    return summed / float(np.prod(k))


@def_op("adaptive_avg_pool2d")
def adaptive_avg_pool2d(x, output_size=1, data_format="NCHW"):
    out = _norm_tuple(output_size, 2)
    if data_format == "NCHW":
        h_axis, w_axis = 2, 3
    else:
        h_axis, w_axis = 1, 2
    H, W = x.shape[h_axis], x.shape[w_axis]
    if H % out[0] == 0 and W % out[1] == 0:
        kh, kw = H // out[0], W // out[1]
        window = [1, 1, 1, 1]
        window[h_axis], window[w_axis] = kh, kw
        summed = lax.reduce_window(x, 0.0, lax.add, tuple(window), tuple(window),
                                   [(0, 0)] * 4)
        return summed / float(kh * kw)
    # general case: mean over index buckets
    return jax.image.resize(x, tuple(
        out[ (0 if i == h_axis else 1) ] if i in (h_axis, w_axis) else d
        for i, d in enumerate(x.shape)), method="linear")


@def_op("adaptive_max_pool2d")
def adaptive_max_pool2d(x, output_size=1, data_format="NCHW"):
    out = _norm_tuple(output_size, 2)
    H, W = (x.shape[2], x.shape[3]) if data_format == "NCHW" else (x.shape[1], x.shape[2])
    kh, kw = H // out[0], W // out[1]
    window = (1, 1, kh, kw) if data_format == "NCHW" else (1, kh, kw, 1)
    return lax.reduce_window(x, -jnp.inf, lax.max, window, window, [(0, 0)] * 4)


@def_op("interpolate")
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    if data_format == "NCHW":
        H, W = x.shape[2], x.shape[3]
    else:
        H, W = x.shape[1], x.shape[2]
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (tuple, list)) else (
            scale_factor, scale_factor)
        size = (int(H * sf[0]), int(W * sf[1]))
    size = tuple(int(s) for s in size)
    if data_format == "NCHW":
        new_shape = x.shape[:2] + size
    else:
        new_shape = (x.shape[0],) + size + (x.shape[-1],)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "linear": "linear", "area": "linear"}[mode]
    return jax.image.resize(x, new_shape, method=method)


@def_op("unfold")
def unfold(x, kernel_sizes=3, strides=1, paddings=0, dilations=1):
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    p = _norm_tuple(paddings, 2)
    d = _norm_tuple(dilations, 2)
    N, C = x.shape[0], x.shape[1]
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(N, C * k[0] * k[1], -1)


# ---------------------------------------------------------------------------
# Normalisation
# ---------------------------------------------------------------------------


@def_op("layer_norm")
def layer_norm(x, weight=None, bias=None, epsilon=1e-5, begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@def_op("rms_norm")
def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1):
    """(reference: phi/kernels/gpu/rms_norm_kernel.cu; SPMD rule
    infermeta/spmd_rules/rms_norm.cc). Accumulates in fp32 like the ref;
    on TPU the fused Pallas kernel handles the common last-axis case."""
    if (weight is not None and bias is None
            and begin_norm_axis in (-1, x.ndim - 1)
            and weight.ndim == 1):
        from ..core import flags as _flags

        if _flags._get("use_pallas_kernels", True):
            try:
                import jax as _jax

                if "tpu" in str(_jax.devices()[0].platform).lower():
                    from .pallas.rms_norm import rms_norm_fused

                    return rms_norm_fused(x, weight, float(epsilon))
            except Exception:
                pass
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
    var = jnp.mean(jnp.square(xf), axis=axes, keepdims=True)
    out = xf * lax.rsqrt(var + epsilon)
    out = out.astype(dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@def_op("batch_norm")
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    """Returns (out, new_running_mean, new_running_var)."""
    if x.ndim == 2:
        axes, shape = (0,), (1, -1)
    elif data_format == "NCHW":
        axes, shape = (0, 2, 3) if x.ndim == 4 else (0, 2), (1, -1) + (1,) * (x.ndim - 2)
    else:
        axes, shape = tuple(range(x.ndim - 1)), (1,) * (x.ndim - 1) + (-1,)
    if training:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        n = x.size // mean.size
        unbiased = var * n / max(n - 1, 1)
        new_rm = momentum * running_mean + (1 - momentum) * mean
        new_rv = momentum * running_var + (1 - momentum) * unbiased
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    out = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, new_rm, new_rv


@def_op("group_norm")
def group_norm(x, weight=None, bias=None, epsilon=1e-5, groups=1,
               data_format="NCHW"):
    N, C = x.shape[0], x.shape[1]
    xg = x.reshape(N, groups, C // groups, *x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = (1, C) + (1,) * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@def_op("instance_norm")
def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + epsilon)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@def_op("fused_layer_norm_residual")
def fused_layer_norm_residual(x, residual, weight=None, bias=None,
                              epsilon=1e-5):
    """add-residual + layernorm fused (reference:
    phi/kernels/fusion/gpu/fused_layernorm_kernel.cu); XLA fuses these on
    TPU so the "kernel" is just the composite, kept as one op for parity."""
    y = x + residual
    mean = jnp.mean(y, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(y - mean), axis=-1, keepdims=True)
    out = (y - mean) * lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out, y


# ---------------------------------------------------------------------------
# Dropout / embedding
# ---------------------------------------------------------------------------


@def_op("dropout")
def dropout(x, key, p=0.5, training=True, mode="upscale_in_train"):
    if not training or p == 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


@def_op("embedding")
def embedding(ids, weight, padding_idx=None, sparse=False):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@def_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1):
    """Returns per-example loss (no reduction), paddle semantics."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        safe = jnp.where(lbl == ignore_index, 0, lbl)
        picked = jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32),
                                     axis=axis)
        loss = -jnp.where((lbl == ignore_index)[..., None], 0.0, picked)
    return loss


@def_op("cross_entropy_loss")
def cross_entropy_loss(logits, label, weight=None, soft_label=False,
                       ignore_index=-100, reduction="mean", axis=-1,
                       label_smoothing=0.0):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    n_class = logits.shape[axis]
    if soft_label:
        target = label
        loss = -jnp.sum(target * logp, axis=axis)
        valid = jnp.ones(loss.shape, jnp.float32)
    else:
        lbl = label
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        valid = (lbl != ignore_index).astype(jnp.float32)
        safe = jnp.where(lbl == ignore_index, 0, lbl).astype(jnp.int32)
        if label_smoothing > 0.0:
            onehot = jax.nn.one_hot(safe, n_class, dtype=logp.dtype, axis=axis)
            target = onehot * (1 - label_smoothing) + label_smoothing / n_class
            loss = -jnp.sum(target * logp, axis=axis) * valid
        else:
            picked = jnp.take_along_axis(logp, safe[..., None], axis=axis)
            loss = -jnp.squeeze(picked, axis=axis) * valid
        if weight is not None:
            w = jnp.take(weight, safe, axis=0) * valid
            loss = loss * jnp.take(weight, safe, axis=0)
            valid = w
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid), 1.0)
        return jnp.sum(loss) / denom
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@def_op("mse_loss")
def mse_loss(input, label, reduction="mean"):
    return _reduce_loss(jnp.square(input - label), reduction)


@def_op("l1_loss")
def l1_loss(input, label, reduction="mean"):
    return _reduce_loss(jnp.abs(input - label), reduction)


@def_op("smooth_l1_loss")
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta, diff - 0.5 * delta)
    return _reduce_loss(loss, reduction)


@def_op("nll_loss")
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    valid = (label != ignore_index)
    safe = jnp.where(valid, label, 0).astype(jnp.int32)
    picked = -jnp.take_along_axis(input, safe[..., None], axis=-1)[..., 0]
    w = jnp.ones_like(picked) if weight is None else jnp.take(weight, safe, axis=0)
    w = w * valid.astype(picked.dtype)
    loss = picked * w
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce_loss(loss, reduction)


@def_op("binary_cross_entropy")
def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(input, eps, None))
             + (1 - label) * jnp.log(jnp.clip(1 - input, eps, None)))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


@def_op("binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (
            jnp.log(1 + jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + max_val + jnp.log(
            jnp.exp(-max_val) + jnp.exp(-logit - max_val))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


@def_op("kl_div")
def kl_div(input, label, reduction="mean"):
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    return _reduce_loss(loss, reduction)


@def_op("cosine_similarity")
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


# ---------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------


@def_op("scaled_dot_product_attention")
def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, scale=None,
                                 dropout_key=None):
    """Layout [batch, seqlen, num_heads, head_dim] (paddle flash_attention
    layout, nn/functional/flash_attention.py:147). XLA fallback path; the
    Pallas flash kernel registers over this on TPU."""
    B, S, H, D = q.shape
    scale = scale or (1.0 / np.sqrt(D))
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # B,H,S,D
    kf = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vf = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if is_causal:
        Sk = kf.shape[2]
        mask = jnp.tril(jnp.ones((S, Sk), bool), k=Sk - S)
        scores = jnp.where(mask, scores, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -jnp.inf)
        else:
            scores = scores + attn_mask
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p:
        if dropout_key is None:
            raise ValueError(
                "attention dropout requires an explicit dropout_key; call "
                "through nn.functional.scaled_dot_product_attention / "
                "flash_attention, which thread one from the global RNG")
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vf)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def rotate_half(x):
    """[-x2, x1] pairing used by neox-style rotary embeddings."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


@def_op("fused_rope")
def fused_rope(q, k, cos, sin, position_ids=None):
    """Rotary embedding applied to q,k [B,S,H,D] (reference:
    phi/kernels/fusion/gpu/fused_rope_kernel.cu; spmd_rules/fused_rope.cc).
    cos/sin: [S, D] or [1, S, 1, D]."""
    rot = rotate_half

    c = cos.reshape(1, cos.shape[-2], 1, cos.shape[-1]) if cos.ndim == 2 else cos
    s = sin.reshape(1, sin.shape[-2], 1, sin.shape[-1]) if sin.ndim == 2 else sin
    if position_ids is not None:
        c = jnp.take(c[0, :, 0], position_ids, axis=0)[:, :, None, :]
        s = jnp.take(s[0, :, 0], position_ids, axis=0)[:, :, None, :]
    q_out = q * c + rot(q) * s
    k_out = k * c + rot(k) * s
    return q_out, k_out


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------


@def_op("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1):
    n = label.shape[-1]
    if prior_dist is None:
        return (1 - epsilon) * label + epsilon / n
    return (1 - epsilon) * label + epsilon * prior_dist


@def_op("temporal_shift")
def temporal_shift(x, seg_num=1, shift_ratio=0.25, data_format="NCHW"):
    NT, C, H, W = x.shape
    N = NT // seg_num
    xr = x.reshape(N, seg_num, C, H, W)
    c1 = int(C * shift_ratio)
    c2 = int(C * 2 * shift_ratio)
    pad = jnp.zeros_like(xr[:, :1])
    left = jnp.concatenate([xr[:, 1:, :c1], pad[:, :, :c1]], axis=1)
    right = jnp.concatenate([pad[:, :, c1:c2], xr[:, :-1, c1:c2]], axis=1)
    rest = xr[:, :, c2:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(NT, C, H, W)


@def_op("pixel_shuffle")
def pixel_shuffle(x, upscale_factor=1, data_format="NCHW"):
    r = upscale_factor
    N, C, H, W = x.shape
    x = x.reshape(N, C // (r * r), r, r, H, W)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(N, C // (r * r), H * r, W * r)
