"""Long-tail op surface (reference: paddle/phi/api/yaml/ops.yaml +
legacy_ops.yaml rows without a previous counterpart here — indexing,
random distributions, special functions, 3-D conv/pool, shuffle/fold
layout ops). Everything lowers to jnp/lax HLOs; ops whose OUTPUT SHAPE
is data-dependent (masked_select, unique_consecutive, edit_distance)
run host-side by design, like geometric.sampling.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core import rng
from ..core.dispatch import def_op
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.enforce import enforce
from ..tensor import Tensor, to_tensor

__all__ = [
    "index_add", "index_put", "masked_select", "fill_diagonal",
    "fill_diagonal_tensor", "renorm", "crop", "multiplex", "dist",
    "shape", "increment", "reverse",
    "broadcast_tensors", "as_complex", "as_real", "complex",
    "tril_indices", "triu_indices", "logspace", "unique_consecutive",
    "bitwise_left_shift", "bitwise_right_shift", "gather_tree", "cummin",
    "channel_shuffle", "pixel_unshuffle", "fold", "max_pool2d_with_index",
    "max_unpool2d", "edit_distance", "top_p_sampling", "i0e", "i1", "i1e",
    "gammaln", "gammaincc", "poisson", "standard_gamma", "dirichlet",
    "binomial", "exponential_", "conv3d", "max_pool3d",
    "avg_pool3d", "stanh", "thresholded_relu", "maxout", "rrelu",
    "log_sigmoid", "equal_all", "is_empty", "clip_by_norm",
    "squared_l2_norm", "shard_index", "huber_loss",
]


# ---------------------------------------------------------------------------
# indexing / manipulation
# ---------------------------------------------------------------------------
@def_op("index_add")
def index_add(x, index, axis, value):
    """x with value rows scatter-ADDED at ``index`` along ``axis``."""
    axis = int(axis)
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


@def_op("index_put_op")
def _index_put(x, value, accumulate, *indices):
    ref = x.at[tuple(indices)]
    return ref.add(value) if accumulate else ref.set(value)


def index_put(x, indices, value, accumulate=False, name=None):
    """x[indices] = value (or += with accumulate) — indices is a tuple
    of integer index arrays, numpy advanced-indexing style."""
    idx = tuple(indices) if isinstance(indices, (list, tuple)) \
        else (indices,)
    return _index_put(x, value, bool(accumulate), *idx)


def masked_select(x, mask, name=None):
    """1-D tensor of elements where mask is True (host-side: the output
    LENGTH is data-dependent)."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    mv = np.asarray(mask._value if isinstance(mask, Tensor) else mask)
    return to_tensor(xv[np.broadcast_to(mv, xv.shape)])


@def_op("fill_diagonal")
def fill_diagonal(x, value, offset=0, wrap=False):
    enforce(x.ndim == 2, lambda: "fill_diagonal expects a 2-D tensor")
    R, C = x.shape
    if wrap and R > C:
        # numpy/reference wrap semantics: the filled flat indices are
        # offset + k*(C+1), i.e. (row*C + col) = offset mod C+1, which
        # with C = -1 mod C+1 reduces to col = (row + offset) mod C+1
        rows = jnp.arange(R)[:, None]
        cols = jnp.arange(C)[None, :]
        mask = (rows + int(offset)) % (C + 1) == cols
    else:
        mask = jnp.eye(R, C, k=int(offset), dtype=bool)
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@def_op("fill_diagonal_tensor")
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    enforce(x.ndim == 2 and int(dim1) == 0 and int(dim2) == 1,
            lambda: "fill_diagonal_tensor here supports 2-D (dim1=0, "
                    "dim2=1)")
    n = min(x.shape[0], x.shape[1]) - abs(int(offset))
    ii = jnp.arange(n)
    rows = ii - min(int(offset), 0)
    cols = ii + max(int(offset), 0)
    return x.at[rows, cols].set(y[:n].astype(x.dtype))


@def_op("renorm")
def renorm(x, p, axis, max_norm):
    """Clip each slice along ``axis`` to p-norm <= max_norm (reference:
    renorm op)."""
    axis = int(axis) % x.ndim
    red = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=red, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * scale.astype(x.dtype)


@def_op("crop")
def crop(x, shape=None, offsets=None):
    out_shape = [d if d != -1 else x.shape[i] - (offsets[i] if offsets
                 else 0) for i, d in enumerate(shape)]
    offs = list(offsets) if offsets is not None else [0] * x.ndim
    return lax.dynamic_slice(x, offs, out_shape)


@def_op("multiplex_op")
def _multiplex(index, *inputs):
    stacked = jnp.stack(inputs)                   # [K, B, ...]
    idx = index.reshape((1, -1) + (1,) * (stacked.ndim - 2))
    return jnp.take_along_axis(stacked, idx.astype(jnp.int32),
                               axis=0)[0]


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors: out[i] =
    inputs[index[i]][i]."""
    return _multiplex(index, *inputs)


@def_op("dist")
def dist(x, y, p=2):
    d = (x - y).reshape(-1)
    p = float(p)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(x.dtype)
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@def_op("shape_op", differentiable=False)
def _shape(x):
    return jnp.asarray(x.shape, jnp.int32)


def shape(x, name=None):
    return _shape(x)


@def_op("increment")
def increment(x, value=1.0):
    return x + jnp.asarray(value, x.dtype)


def reverse(x, axis, name=None):
    from .manipulation import flip

    return flip(x, axis)


@def_op("broadcast_tensors_op")
def _broadcast_tensors(*xs):
    shape = jnp.broadcast_shapes(*[x.shape for x in xs])
    return tuple(jnp.broadcast_to(x, shape) for x in xs)


def broadcast_tensors(inputs, name=None):
    return list(_broadcast_tensors(*inputs))


@def_op("as_complex")
def as_complex(x):
    enforce(x.shape[-1] == 2,
            lambda: "as_complex expects trailing dim 2 (re, im)")
    return lax.complex(x[..., 0], x[..., 1])


@def_op("as_real")
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@def_op("complex")
def complex(real, imag):  # noqa: A001
    return lax.complex(real, imag)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, int(offset), col or row)
    return to_tensor(np.stack([r, c]).astype(str(convert_dtype(dtype))))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, int(offset), col or row)
    return to_tensor(np.stack([r, c]).astype(str(convert_dtype(dtype))))


@def_op("logspace", differentiable=False)
def logspace(start, stop, num, base=10.0, dtype="float32"):
    return jnp.logspace(start, stop, int(num), base=base,
                        dtype=convert_dtype(dtype))


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    """Deduplicate consecutive repeats (host-side: output length is
    data-dependent)."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    enforce(axis is None, "unique_consecutive here supports axis=None")
    flat = xv.reshape(-1)
    if flat.size == 0:
        keep = np.zeros(0, bool)
    else:
        keep = np.concatenate([[True], flat[1:] != flat[:-1]])
    out = [to_tensor(flat[keep])]
    if return_inverse:
        out.append(to_tensor(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.nonzero(keep)[0]
        out.append(to_tensor(np.diff(np.append(idx, flat.size))))
    return out[0] if len(out) == 1 else tuple(out)


@def_op("bitwise_left_shift")
def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


@def_op("bitwise_right_shift")
def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)


@def_op("gather_tree", differentiable=False)
def gather_tree(ids, parents):
    """Beam-search backtrace (reference: gather_tree op): walk parent
    pointers from the last step — one lax.scan, TPU-resident.
    ids/parents: [T, B, beam]."""
    T = ids.shape[0]

    def step(beam_idx, t):
        # beam_idx [B, beam] points into step t's beams
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=-1)
        par = jnp.take_along_axis(parents[t], beam_idx, axis=-1)
        return par, tok

    init = jnp.broadcast_to(jnp.arange(ids.shape[2]), ids.shape[1:])
    _, toks = lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return toks[::-1]


@def_op("cummin")
def cummin(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return lax.associative_scan(jnp.minimum, x, axis=int(axis))


@def_op("channel_shuffle")
def channel_shuffle(x, groups, data_format="NCHW"):
    enforce(data_format == "NCHW", "channel_shuffle supports NCHW")
    b, c, h, w = x.shape
    return x.reshape(b, int(groups), c // int(groups), h, w) \
        .swapaxes(1, 2).reshape(b, c, h, w)


@def_op("pixel_unshuffle")
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    enforce(data_format == "NCHW", "pixel_unshuffle supports NCHW")
    r = int(downscale_factor)
    b, c, h, w = x.shape
    x = x.reshape(b, c, h // r, r, w // r, r)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(b, c * r * r, h // r,
                                                 w // r)


@def_op("fold")
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1):
    """col2im — inverse of unfold (reference: fold op). x is
    [B, C*kh*kw, L]."""
    def pair(v):
        return (int(v), int(v)) if np.isscalar(v) else (int(v[0]),
                                                        int(v[1]))

    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    B, ckk, L = x.shape
    C = ckk // (kh * kw)
    nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    enforce(L == nh * nw, lambda: f"fold: L={L} != {nh}*{nw}")
    cols = x.reshape(B, C, kh, kw, nh, nw)
    out = jnp.zeros((B, C, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):          # static small loops: unrolled scatter
        for j in range(kw):
            ys = i * dh + sh * jnp.arange(nh)
            xs = j * dw + sw * jnp.arange(nw)
            out = out.at[:, :, ys[:, None], xs[None, :]].add(
                cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


@def_op("max_pool2d_with_index")
def max_pool2d_with_index(x, kernel_size, stride=None, padding=0):
    """Max pool returning (out, flat argmax indices) — the reference's
    max_pool2d_with_index feeding max_unpool2d."""
    k = (kernel_size, kernel_size) if np.isscalar(kernel_size) \
        else tuple(kernel_size)
    s = k if stride is None else ((stride, stride) if np.isscalar(stride)
                                  else tuple(stride))
    p = (padding, padding) if np.isscalar(padding) else tuple(padding)
    B, C, H, W = x.shape
    neg = jnp.finfo(jnp.float32).min
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (0, 0), (p[0], p[0]),
                                         (p[1], p[1])),
                 constant_values=neg)
    lin = jnp.arange(H * W, dtype=jnp.int32).reshape(1, 1, H, W)
    lin = jnp.pad(lin, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
    oh = (H + 2 * p[0] - k[0]) // s[0] + 1
    ow = (W + 2 * p[1] - k[1]) // s[1] + 1
    patches = []
    idxs = []
    for i in range(k[0]):
        for j in range(k[1]):
            patches.append(lax.slice(
                xp, (0, 0, i, j),
                (B, C, i + (oh - 1) * s[0] + 1, j + (ow - 1) * s[1] + 1),
                (1, 1, s[0], s[1])))
            idxs.append(lax.slice(
                lin, (0, 0, i, j),
                (1, 1, i + (oh - 1) * s[0] + 1, j + (ow - 1) * s[1] + 1),
                (1, 1, s[0], s[1])))
    stackv = jnp.stack(patches)                   # [kk, B, C, oh, ow]
    stacki = jnp.stack(idxs)                      # [kk, 1, 1, oh, ow]
    arg = jnp.argmax(stackv, axis=0)              # [B, C, oh, ow]
    out = jnp.max(stackv, axis=0).astype(x.dtype)
    flat_idx = jnp.take_along_axis(
        jnp.broadcast_to(stacki, stackv.shape), arg[None], axis=0)[0]
    return out, flat_idx.astype(jnp.int32)


@def_op("max_unpool2d")
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    """Scatter pooled values back to their argmax positions."""
    B, C, oh, ow = x.shape
    if output_size is not None:
        H, W = int(output_size[-2]), int(output_size[-1])
    else:
        k = kernel_size if not np.isscalar(kernel_size) \
            else (kernel_size, kernel_size)
        s = k if stride is None else (
            (stride, stride) if np.isscalar(stride) else stride)
        pd = (padding, padding) if np.isscalar(padding) else tuple(padding)
        H = (oh - 1) * s[0] + k[0] - 2 * pd[0]
        W = (ow - 1) * s[1] + k[1] - 2 * pd[1]
    out = jnp.zeros((B, C, H * W), x.dtype).at[
        jnp.arange(B)[:, None, None], jnp.arange(C)[None, :, None],
        indices.reshape(B, C, -1)].set(x.reshape(B, C, -1))
    return out.reshape(B, C, H, W)


def edit_distance(hyps, refs, normalized=True, name=None):
    """Levenshtein distance per pair (host DP: ragged, data-dependent)."""
    def one(h, r):
        m, n = len(h), len(r)
        d = np.arange(n + 1, dtype=np.float32)
        for i in range(1, m + 1):
            prev = d.copy()
            d[0] = i
            for j in range(1, n + 1):
                d[j] = min(prev[j] + 1, d[j - 1] + 1,
                           prev[j - 1] + (h[i - 1] != r[j - 1]))
        return d[n] / (n if normalized and n else 1)

    hs = [np.asarray(h._value if isinstance(h, Tensor) else h).tolist()
          for h in hyps]
    rs = [np.asarray(r._value if isinstance(r, Tensor) else r).tolist()
          for r in refs]
    return to_tensor(np.asarray([one(h, r) for h, r in zip(hs, rs)],
                                np.float32))


@def_op("top_p_sampling", differentiable=False)
def _top_p_sampling(key, logits, p):
    # p: [B] per-row nucleus thresholds
    srt = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < p[:, None], axis=-1)
    cutoff = jnp.take_along_axis(srt, cutoff_idx[:, None], axis=-1)
    masked = jnp.where(logits < cutoff, -1e30, logits)
    ids = jax.random.categorical(key, masked, axis=-1)
    scores = jnp.take_along_axis(jax.nn.softmax(logits, -1), ids[:, None],
                                 axis=-1)
    return scores, ids[:, None]


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """(reference: top_p_sampling op — the serving nucleus sampler).
    ``ps`` is a per-row [B] threshold tensor or a scalar; ``seed``
    (when >= 0) makes the draw reproducible."""
    B = (x.shape[0] if hasattr(x, "shape") else 1)
    if np.isscalar(ps):
        pv = jnp.full((B,), float(ps), jnp.float32)
    else:
        pv = jnp.asarray(ps._value if isinstance(ps, Tensor)
                         else ps, jnp.float32).reshape(-1)
    key = jax.random.PRNGKey(int(seed)) if (seed is not None
                                            and int(seed) >= 0) \
        else rng.get_key()
    return _top_p_sampling(key, x, pv)


# ---------------------------------------------------------------------------
# special functions
# ---------------------------------------------------------------------------
@def_op("i0e")
def i0e(x):
    return jax.scipy.special.i0e(x)


@def_op("i1")
def i1(x):
    return jax.scipy.special.i1(x)


@def_op("i1e")
def i1e(x):
    return jax.scipy.special.i1e(x)


@def_op("gammaln")
def gammaln(x):
    return jax.scipy.special.gammaln(x)


@def_op("gammaincc")
def gammaincc(x, y):
    return jax.scipy.special.gammaincc(x, y)


# ---------------------------------------------------------------------------
# random distributions (key-first kernels; public fns draw from the
# global stream, matching ops/creation.py's convention)
# ---------------------------------------------------------------------------
@def_op("poisson_op", differentiable=False)
def _poisson(key, lam):
    return jax.random.poisson(key, lam).astype(lam.dtype)


def poisson(x, name=None):
    return _poisson(rng.get_key(), x)


@def_op("standard_gamma_op", differentiable=False)
def _standard_gamma(key, alpha):
    return jax.random.gamma(key, alpha)


def standard_gamma(x, name=None):
    return _standard_gamma(rng.get_key(), x)


@def_op("dirichlet_op", differentiable=False)
def _dirichlet(key, alpha):
    g = jax.random.gamma(key, alpha)
    return g / jnp.sum(g, axis=-1, keepdims=True)


def dirichlet(alpha, name=None):
    return _dirichlet(rng.get_key(), alpha)


@def_op("binomial_op", differentiable=False)
def _binomial(key, n, p, nmax):
    # sum of Bernoulli draws via uniform comparison, vectorized over the
    # host-read static max trial count
    nmax = int(nmax)
    u = jax.random.uniform(key, (nmax,) + p.shape)
    trials = jnp.arange(nmax).reshape((nmax,) + (1,) * p.ndim)
    live = trials < jnp.asarray(n)[None]
    return jnp.sum((u < p) & live, axis=0).astype(jnp.int32)


def binomial(count, prob, name=None):
    cv = np.asarray(count._value if isinstance(count, Tensor) else count)
    nmax = int(cv.max()) if cv.size else 0
    return _binomial(rng.get_key(), count, prob, nmax)


def exponential_(x, lam=1.0, name=None):
    """In-place exponential fill (reference: exponential_ inplace op) —
    functional value-swap here (immutable arrays)."""
    from .api_tail import _random_fill

    key = rng.get_key()
    return _random_fill(
        x, jax.random.exponential(key, tuple(x.shape)) / float(lam))


# ---------------------------------------------------------------------------
# 3-D conv / pool
# ---------------------------------------------------------------------------
@def_op("conv3d")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NCDHW"):
    enforce(data_format == "NCDHW", "conv3d supports NCDHW")

    def trip(v):
        return (int(v),) * 3 if np.isscalar(v) else tuple(int(i)
                                                          for i in v)

    out = lax.conv_general_dilated(
        x, weight, trip(stride), [(p, p) for p in trip(padding)],
        rhs_dilation=trip(dilation), feature_group_count=int(groups),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1, 1)
    return out




# max_pool3d / avg_pool3d moved to ops/pool3d.py (full reference
# surface: return_mask, max_unpool3d, exclusive/divisor_override);
# thin delegations kept for the MaxPool3D/AvgPool3D layer classes
def max_pool3d(x, kernel_size, stride=None, padding=0, **kw):
    from .pool3d import max_pool3d as _mp3

    return _mp3(x, kernel_size, stride, padding, **kw)


def avg_pool3d(x, kernel_size, stride=None, padding=0, **kw):
    from .pool3d import avg_pool3d as _ap3

    kw.setdefault("exclusive", True)
    return _ap3(x, kernel_size, stride, padding, **kw)


# ---------------------------------------------------------------------------
# activations & small losses
# ---------------------------------------------------------------------------
@def_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@def_op("thresholded_relu")
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, jnp.zeros_like(x))


@def_op("maxout")
def maxout(x, groups, axis=1):
    axis = int(axis) % x.ndim
    c = x.shape[axis]
    enforce(c % int(groups) == 0,
            lambda: f"maxout: channels {c} % groups {groups} != 0")
    new_shape = (x.shape[:axis] + (c // int(groups), int(groups))
                 + x.shape[axis + 1:])
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


@def_op("rrelu_op")
def _rrelu(key, x, lower, upper, training):
    if training:
        a = jax.random.uniform(key, x.shape, minval=lower, maxval=upper)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, (a * x).astype(x.dtype))


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=True, name=None):
    enforce(0 <= lower <= upper <= 1,
            lambda: f"rrelu needs 0 <= lower <= upper <= 1, got "
                    f"{lower}, {upper}")
    return _rrelu(rng.get_key(), x, float(lower), float(upper),
                  bool(training))


@def_op("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@def_op("equal_all", differentiable=False)
def equal_all(x, y):
    if x.shape != y.shape:
        return jnp.asarray(False)
    return jnp.all(x == y)


@def_op("is_empty", differentiable=False)
def is_empty(x):
    return jnp.asarray(int(np.prod(x.shape)) == 0 if x.shape else False)


@def_op("clip_by_norm")
def clip_by_norm(x, max_norm):
    # clamp the sum-of-squares: sqrt'(0) is inf and would NaN the VJP
    # even under a zero cotangent (0 * inf)
    norm = jnp.sqrt(jnp.maximum(jnp.sum(jnp.square(x)), 1e-30))
    safe = jnp.where(norm > max_norm, norm, jnp.ones_like(norm))
    return jnp.where(norm > max_norm, x * (max_norm / safe), x)


@def_op("squared_l2_norm")
def squared_l2_norm(x):
    return jnp.sum(jnp.square(x)).reshape(())


@def_op("shard_index", differentiable=False)
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Map global ids to shard-local ids (reference: shard_index op —
    the PS-era embedding sharding helper)."""
    shard_size = (int(index_num) + int(nshards) - 1) // int(nshards)
    lo = int(shard_id) * shard_size
    local = input - lo
    inside = (input >= lo) & (input < lo + shard_size)
    return jnp.where(inside, local,
                     jnp.asarray(ignore_value, input.dtype))


@def_op("huber_loss")
def huber_loss(input, label, delta=1.0, reduction="mean"):
    d = input - label
    ad = jnp.abs(d)
    loss = jnp.where(ad <= delta, 0.5 * d * d,
                     delta * (ad - 0.5 * delta))
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss
