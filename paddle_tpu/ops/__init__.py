"""Operator library: every kernel is a pure JAX function registered in
core/registry.py (the PHI-kernel analog). Submodules by category, mirroring
the reference's python/paddle/tensor/ split."""
from . import creation, extra, linalg, manipulation, math, nn_ops  # noqa: F401
from . import api_tail  # noqa: F401  (after math/extra: generates foo_ over them)
from .creation import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .extra import *  # noqa: F401,F403
from .api_tail import *  # noqa: F401,F403
