"""Tensor creation operators.

(reference: python/paddle/tensor/creation.py and random.py; phi kernels
full_kernel/gaussian_kernel/uniform_kernel etc.)
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

from ..core import rng
from ..core.dispatch import def_op
from ..core.dtype import convert_dtype, get_default_dtype
from ..tensor import Tensor, to_tensor

# -- deterministic creation -------------------------------------------------


@def_op("zeros", differentiable=False)
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(shape, dtype=convert_dtype(dtype))


def zeros(shape, dtype=None, name=None):
    return _zeros(shape=tuple(shape), dtype=str(convert_dtype(dtype or get_default_dtype())))


@def_op("ones", differentiable=False)
def _ones(shape=(), dtype="float32"):
    return jnp.ones(shape, dtype=convert_dtype(dtype))


def ones(shape, dtype=None, name=None):
    return _ones(shape=tuple(shape), dtype=str(convert_dtype(dtype or get_default_dtype())))


@def_op("full", differentiable=False)
def _full(shape=(), fill_value=0.0, dtype="float32"):
    return jnp.full(shape, fill_value, dtype=convert_dtype(dtype))


def full(shape, fill_value, dtype=None, name=None):
    if dtype is None:
        dtype = get_default_dtype() if isinstance(fill_value, float) else (
            "bool" if isinstance(fill_value, bool) else "int64")
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return _full(shape=tuple(shape), fill_value=fill_value, dtype=str(convert_dtype(dtype)))


@def_op("zeros_like")
def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=convert_dtype(dtype) if dtype else None)


@def_op("ones_like")
def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=convert_dtype(dtype) if dtype else None)


@def_op("full_like")
def full_like(x, fill_value=0.0, dtype=None):
    return jnp.full_like(x, fill_value, dtype=convert_dtype(dtype) if dtype else None)


@def_op("arange", differentiable=False)
def _arange(start=0, end=None, step=1, dtype="int64"):
    return jnp.arange(start, end, step, dtype=convert_dtype(dtype))


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("float32" if any(isinstance(v, float) for v in (start, end, step))
                 else "int64")
    return _arange(start=start, end=end, step=step, dtype=str(convert_dtype(dtype)))


@def_op("linspace", differentiable=False)
def _linspace(start=0.0, stop=1.0, num=100, dtype="float32"):
    return jnp.linspace(start, stop, num, dtype=convert_dtype(dtype))


def linspace(start, stop, num, dtype=None, name=None):
    return _linspace(start=float(start), stop=float(stop), num=int(num),
                     dtype=str(convert_dtype(dtype or get_default_dtype())))


@def_op("eye", differentiable=False)
def _eye(num_rows=1, num_columns=None, dtype="float32"):
    return jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return _eye(num_rows=int(num_rows),
                num_columns=int(num_columns) if num_columns else None,
                dtype=str(convert_dtype(dtype or get_default_dtype())))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype=dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype=dtype)


@def_op("meshgrid_op")
def _meshgrid(*xs, indexing="ij"):
    return tuple(jnp.meshgrid(*xs, indexing=indexing))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    return list(_meshgrid(*args))


def diagflat(x, offset=0, name=None):
    from . import manipulation
    return manipulation.diag(x) if False else to_tensor(
        jnp.diagflat(x._value if isinstance(x, Tensor) else x, k=offset))


# -- random creation --------------------------------------------------------
# Random ops take the PRNG key as a tensor input so replay (generic vjp)
# and jitted steps are deterministic given the key.


@def_op("uniform_random", differentiable=False)
def _uniform(key, shape=(), dtype="float32", min=-1.0, max=1.0):
    return jax.random.uniform(key, shape, dtype=convert_dtype(dtype),
                              minval=min, maxval=max)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return _uniform(rng.get_key(), shape=tuple(shape),
                    dtype=str(convert_dtype(dtype or get_default_dtype())),
                    min=float(min), max=float(max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


@def_op("gaussian_random", differentiable=False)
def _gaussian(key, shape=(), dtype="float32", mean=0.0, std=1.0):
    return mean + std * jax.random.normal(key, shape, dtype=convert_dtype(dtype))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    return _gaussian(rng.get_key(), shape=tuple(shape or ()), mean=float(mean),
                     std=float(std), dtype=str(get_default_dtype()))


def randn(shape, dtype=None, name=None):
    return _gaussian(rng.get_key(), shape=tuple(shape),
                     dtype=str(convert_dtype(dtype or get_default_dtype())))


@def_op("randint_op", differentiable=False)
def _randint(key, low=0, high=None, shape=(), dtype="int64"):
    return jax.random.randint(key, shape, low, high, dtype=convert_dtype(dtype))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return _randint(rng.get_key(), low=int(low), high=int(high),
                    shape=tuple(shape), dtype=str(convert_dtype(dtype or "int64")))


@def_op("randperm_op", differentiable=False)
def _randperm(key, n=1, dtype="int64"):
    return jax.random.permutation(key, n).astype(convert_dtype(dtype))


def randperm(n, dtype="int64", name=None):
    return _randperm(rng.get_key(), n=int(n), dtype=str(convert_dtype(dtype)))


@def_op("bernoulli_op", differentiable=False)
def _bernoulli(x, key):
    return jax.random.bernoulli(key, x).astype(x.dtype)


def bernoulli(x, name=None):
    return _bernoulli(x, rng.get_key())


@def_op("multinomial_op", differentiable=False)
def _multinomial(x, key, num_samples=1, replacement=False):
    logits = jnp.log(jnp.clip(x, 1e-30, None))
    if replacement:
        return jax.random.categorical(
            key, logits, axis=-1, shape=x.shape[:-1] + (num_samples,)
        ).astype(jnp.int64)
    return jax.random.choice(key, x.shape[-1], (num_samples,), replace=False,
                             p=x / jnp.sum(x)).astype(jnp.int64)


def multinomial(x, num_samples=1, replacement=False, name=None):
    return _multinomial(x, rng.get_key(), num_samples=int(num_samples),
                        replacement=bool(replacement))
