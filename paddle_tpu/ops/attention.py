"""Attention ops: flash attention with Pallas TPU kernel + XLA fallback.

(reference: phi/kernels/gpu/flash_attn_kernel.cu — dynloaded flashattn v2
lib; YAML ops.yaml:1030 with spmd_rule FlashAttInferSpmd. Here the TPU
path is a Pallas kernel (ops/pallas/flash_attention.py) and the portable
path is plain XLA, selected at trace time by backend.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import flags
from ..core.dispatch import def_op
from .nn_ops import scaled_dot_product_attention as _sdpa_public

_sdpa_raw = _sdpa_public.raw


def _use_pallas(q) -> bool:
    if not flags._get("use_pallas_kernels", True):
        return False
    try:
        return "tpu" in str(jax.devices()[0].platform).lower() or \
               "axon" in str(jax.devices()[0].platform).lower()
    except Exception:
        return False


@def_op("flash_attention")
def flash_attention(q, k, v, causal=False, dropout=0.0, dropout_key=None):
    """Layout [batch, seqlen, num_heads, head_dim]."""
    if _use_pallas(q) and not dropout:
        try:
            from .pallas.flash_attention import flash_attention_fwd

            # positional: custom_vjp nondiff args reject keywords
            return flash_attention_fwd(q, k, v, causal, None, None)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # unsupported shape, Mosaic compile
            # failure, platform quirk — keep training alive on the XLA
            # path rather than dying on a kernel-only problem.
            global _warned_fallback
            if not _warned_fallback:
                _warned_fallback = True
                import warnings

                warnings.warn(
                    f"flash_attention: Pallas kernel unavailable "
                    f"({type(e).__name__}: {e}); using XLA fallback")
    return _sdpa_raw(q, k, v, attn_mask=None, dropout_p=dropout,
                     is_causal=causal, dropout_key=dropout_key)


_warned_fallback = False
