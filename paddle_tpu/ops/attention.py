"""Attention ops: flash attention with Pallas TPU kernel + XLA fallback.

(reference: phi/kernels/gpu/flash_attn_kernel.cu — dynloaded flashattn v2
lib; YAML ops.yaml:1030 with spmd_rule FlashAttInferSpmd. Here the TPU
path is a Pallas kernel (ops/pallas/flash_attention.py) and the portable
path is plain XLA, selected at trace time by backend.)
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

from ..core import flags
from ..core.dispatch import def_op
from .nn_ops import scaled_dot_product_attention as _sdpa_public

_sdpa_raw = _sdpa_public.raw


def _use_pallas(q) -> bool:
    if not flags._get("use_pallas_kernels", True):
        return False
    try:
        return "tpu" in str(jax.devices()[0].platform).lower() or \
               "axon" in str(jax.devices()[0].platform).lower()
    except Exception:
        return False


def _gqa_sdpa(q, k, v, causal):
    """Grouped-query attention without materializing repeated K/V:
    q reshapes to [B, KV, rep, S, D] (query head h reads kv head
    h // rep) and the kv planes broadcast over the rep dim — the XLA
    fallback analog of the decode kernel's native GQA grouping."""
    B, S, H, D = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    rep = H // KV
    scale = 1.0 / float(np.sqrt(D))
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32).reshape(B, KV, rep, S, D)
    kf = jnp.swapaxes(k, 1, 2).astype(jnp.float32)          # [B, KV, Sk, D]
    vf = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    scores = jnp.einsum("bkrqd,bktd->bkrqt", qf, kf) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, Sk), bool), k=Sk - S)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkrqt,bktd->bkrqd", probs, vf)
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2).astype(q.dtype)


@def_op("flash_attention")
def flash_attention(q, k, v, causal=False, dropout=0.0, dropout_key=None):
    """Layout [batch, seqlen, num_heads, head_dim]. GQA accepted: k/v
    may carry fewer (dividing) heads — the XLA path broadcasts the
    shared kv plane per query group (no per-query-head K/V copies); the
    Pallas kernel path repeats at the kernel boundary only (the kernel
    requires equal head counts)."""
    Hq, Hk = q.shape[2], k.shape[2]
    if _use_pallas(q) and not dropout:
        try:
            from .pallas.flash_attention import flash_attention_fwd

            kk, vv = k, v
            if Hk != Hq:
                kk = jnp.repeat(k, Hq // Hk, axis=2)
                vv = jnp.repeat(v, Hq // Hk, axis=2)
            # positional: custom_vjp nondiff args reject keywords
            return flash_attention_fwd(q, kk, vv, causal, None, None)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # unsupported shape, Mosaic compile
            # failure, platform quirk — keep training alive on the XLA
            # path rather than dying on a kernel-only problem.
            global _warned_fallback
            if not _warned_fallback:
                _warned_fallback = True
                import warnings

                warnings.warn(
                    f"flash_attention: Pallas kernel unavailable "
                    f"({type(e).__name__}: {e}); using XLA fallback")
    if Hk != Hq and not dropout:
        return _gqa_sdpa(q, k, v, causal)
    if Hk != Hq:
        k = jnp.repeat(k, Hq // Hk, axis=2)
        v = jnp.repeat(v, Hq // Hk, axis=2)
    return _sdpa_raw(q, k, v, attn_mask=None, dropout_p=dropout,
                     is_causal=causal, dropout_key=dropout_key)


_warned_fallback = False


def _segments_from_cu(cu, total):
    """cu_seqlens [n+1] -> per-token segment ids [total] (padding past
    cu[-1] gets id -1, which still self-matches so padded rows stay
    finite and are sliced away by the caller)."""
    cu = jnp.asarray(cu, jnp.int32)
    pos = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu[1:], pos, side="right").astype(jnp.int32)
    return jnp.where(pos < cu[-1], seg, -1)


@def_op("flash_attn_varlen")
def flash_attn_varlen(q, k, v, cu_seqlens_q, cu_seqlens_k, causal=False,
                      scale=None, dropout=0.0, dropout_key=None):
    """Packed varlen attention (reference: flash_attn_unpadded /
    flash_attn_varlen_func, python/paddle/nn/functional/
    flash_attention.py:384 over phi flash_attn_unpadded kernel).

    q/k/v: [total_tokens, H, D] packed concatenations of sequences with
    boundaries cu_seqlens (e.g. [0, s1, s1+s2, ...]). Tokens never
    attend across sequence boundaries. TPU path: the Pallas flash
    kernel with segment-id masking; portable path: dense mask."""
    Tq, H, D = q.shape
    Tk = k.shape[0]
    qseg = _segments_from_cu(cu_seqlens_q, Tq)
    kseg = _segments_from_cu(cu_seqlens_k, Tk)
    q4, k4, v4 = q[None], k[None], v[None]
    # the Pallas kernel's causal mask is the global row>=col frontier,
    # which is only correct when the q and k packs share boundaries
    same_pack = Tq == Tk and (cu_seqlens_q is cu_seqlens_k
                              or not causal)
    if _use_pallas(q) and not dropout and same_pack:
        try:
            from .pallas.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q4, k4, v4, causal, scale, None,
                                       qseg[None], kseg[None])[0]
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            global _warned_fallback
            if not _warned_fallback:
                _warned_fallback = True
                import warnings

                warnings.warn(
                    f"flash_attn_varlen: Pallas kernel unavailable "
                    f"({type(e).__name__}: {e}); using XLA fallback")
    mask = qseg[:, None] == kseg[None, :]
    if causal:
        # per-sequence causal frontier: q row r of sequence s (at
        # in-sequence position qp) sees k columns of s up to
        # qp + (len_k(s) - len_q(s)) — the bottom-right-aligned
        # rectangular convention applied within EACH packed sequence
        cq = jnp.asarray(cu_seqlens_q._value if hasattr(cu_seqlens_q,
                                                        "_value")
                         else cu_seqlens_q, jnp.int32)
        ck = jnp.asarray(cu_seqlens_k._value if hasattr(cu_seqlens_k,
                                                        "_value")
                         else cu_seqlens_k, jnp.int32)
        qs_c = jnp.clip(qseg, 0, cq.shape[0] - 2)
        ks_c = jnp.clip(kseg, 0, ck.shape[0] - 2)
        q_pos = jnp.arange(Tq, dtype=jnp.int32) - cq[qs_c]
        k_pos = jnp.arange(Tk, dtype=jnp.int32) - ck[ks_c]
        len_q = (cq[qs_c + 1] - cq[qs_c])
        len_k = (ck[ks_c + 1] - ck[ks_c])
        frontier = q_pos[:, None] + (len_k[None, :] - len_q[:, None])
        mask = mask & (frontier >= k_pos[None, :])
    out = _sdpa_raw(q4, k4, v4, attn_mask=mask[None, None], scale=scale,
                    dropout_p=dropout, is_causal=False,
                    dropout_key=dropout_key)
    return out[0]
