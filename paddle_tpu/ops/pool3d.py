"""3-D pooling family (reference: phi/kernels/pool_kernel.cc pool3d,
max_pool3d_with_index, unpool3d; python nn/functional/pooling.py
max_pool3d:1241 / avg_pool3d:1108 / max_unpool3d:964 /
adaptive_*_pool3d). XLA reduce_window handles N-d windows natively, so
the 3-D family is the same lax program as 2-D with a depth axis."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op
from ..core.enforce import enforce

__all__ = ["max_pool3d", "avg_pool3d", "max_unpool3d"]
# (adaptive_*_pool3d live in nn/functional_extra.py)


def _t3(v):
    return (int(v),) * 3 if np.isscalar(v) else tuple(int(i) for i in v)


def _spatial_pads(dims, k, s, p, ceil_mode):
    """Per-spatial-dim (lo, hi) pads. ceil_mode adds ASYMMETRIC right
    padding so reduce_window emits the ceil-division output size; a
    window that would start entirely in the right pad is dropped (the
    reference/caffe rule: the last window must start inside the input
    or its left padding)."""
    pads = []
    for d, kk, ss, pp in zip(dims, k, s, p):
        if ceil_mode:
            od = -(-(d + 2 * pp - kk) // ss) + 1
            if (od - 1) * ss >= d + pp:
                od -= 1
        else:
            od = (d + 2 * pp - kk) // ss + 1
        extra = max(0, (od - 1) * ss + kk - (d + 2 * pp))
        pads.append((pp, pp + extra))
    return pads


@def_op("max_pool3d")
def _max_pool3d_op(x, kernel_size=2, stride=None, padding=0,
                   ceil_mode=False, data_format="NCDHW"):
    k = _t3(kernel_size)
    s = _t3(stride if stride is not None else kernel_size)
    p = _t3(padding)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    pads = _spatial_pads(x.shape[2:], k, s, p, ceil_mode)
    # reduce_window pads with `init`, so the ceil-mode right pad is
    # transparent to the max
    return lax.reduce_window(
        x, init, lax.max, (1, 1) + k, (1, 1) + s,
        ((0, 0), (0, 0)) + tuple(pads))


def max_pool3d(x, kernel_size=2, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    enforce(data_format == "NCDHW", "max_pool3d supports NCDHW")
    out = _max_pool3d_op(x, kernel_size, stride, padding, ceil_mode,
                         data_format)
    if not return_mask:
        return out
    return out, _max_pool3d_mask(x, kernel_size, stride, padding,
                                 ceil_mode)


@def_op("max_pool3d_mask", differentiable=False)
def _max_pool3d_mask(x, kernel_size=2, stride=None, padding=0,
                     ceil_mode=False):
    # flat argmax indices over the D*H*W volume (feeds max_unpool3d)
    k = _t3(kernel_size)
    s = _t3(stride if stride is not None else kernel_size)
    p = _t3(padding)
    B, C, D, H, W = x.shape
    neg = jnp.finfo(jnp.float32).min
    pads = _spatial_pads((D, H, W), k, s, p, ceil_mode)
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, 0), (0, 0)) + tuple(pads), constant_values=neg)
    lin = jnp.arange(D * H * W, dtype=jnp.int32).reshape(1, 1, D, H, W)
    lin = jnp.pad(lin, ((0, 0), (0, 0)) + tuple(pads))
    od = (D + sum(pads[0]) - k[0]) // s[0] + 1
    oh = (H + sum(pads[1]) - k[1]) // s[1] + 1
    ow = (W + sum(pads[2]) - k[2]) // s[2] + 1
    vals, idxs = [], []
    for a in range(k[0]):
        for b in range(k[1]):
            for c in range(k[2]):
                lim = (B, C, a + (od - 1) * s[0] + 1,
                       b + (oh - 1) * s[1] + 1, c + (ow - 1) * s[2] + 1)
                st = (1, 1, s[0], s[1], s[2])
                vals.append(lax.slice(xp, (0, 0, a, b, c), lim, st))
                idxs.append(lax.slice(lin, (0, 0, a, b, c),
                                      (1, 1) + lim[2:], st))
    sv = jnp.stack(vals)
    si = jnp.stack(idxs)
    arg = jnp.argmax(sv, axis=0)
    flat = jnp.take_along_axis(jnp.broadcast_to(si, sv.shape),
                               arg[None], axis=0)[0]
    return flat.astype(jnp.int32)


@def_op("avg_pool3d")
def avg_pool3d(x, kernel_size=2, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None,
               data_format="NCDHW"):
    enforce(data_format == "NCDHW", "avg_pool3d supports NCDHW")
    k = _t3(kernel_size)
    s = _t3(stride if stride is not None else kernel_size)
    p = _t3(padding)
    sp = _spatial_pads(x.shape[2:], k, s, p, ceil_mode)
    pads = ((0, 0), (0, 0)) + tuple(sp)
    summed = lax.reduce_window(x, 0.0, lax.add, (1, 1) + k, (1, 1) + s,
                               pads)
    if divisor_override:
        return summed / float(divisor_override)
    if exclusive and (any(p) or any(hi > lo for lo, hi in sp)):
        # exclusive: divide by the count of REAL elements per window
        # (padding — symmetric and the ceil-mode right pad — excluded)
        counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                   (1, 1) + k, (1, 1) + s, pads)
        return summed / counts
    return summed / float(np.prod(k))


@def_op("max_unpool3d")
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW"):
    enforce(data_format == "NCDHW", "max_unpool3d supports NCDHW")
    B, C, od, oh, ow = x.shape
    if output_size is not None:
        D, H, W = (int(output_size[-3]), int(output_size[-2]),
                   int(output_size[-1]))
    else:
        k = _t3(kernel_size)
        s = _t3(stride if stride is not None else kernel_size)
        p = _t3(padding)
        D = (od - 1) * s[0] + k[0] - 2 * p[0]
        H = (oh - 1) * s[1] + k[1] - 2 * p[1]
        W = (ow - 1) * s[2] + k[2] - 2 * p[2]
    out = jnp.zeros((B, C, D * H * W), x.dtype).at[
        jnp.arange(B)[:, None, None], jnp.arange(C)[None, :, None],
        indices.reshape(B, C, -1)].set(x.reshape(B, C, -1))
    return out.reshape(B, C, D, H, W)
