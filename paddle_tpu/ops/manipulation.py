"""Shape/layout manipulation operators.

(reference: python/paddle/tensor/manipulation.py; view kernels in
paddle/phi/kernels/stride/ — on TPU all "views" are value-semantic XLA
ops that the compiler folds into layouts, so no stride machinery needed.)
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op

_pyslice = builtins.slice

# ---------------------------------------------------------------------------


@def_op("reshape")
def reshape(x, shape=()):
    return jnp.reshape(x, shape)


@def_op("transpose")
def transpose(x, perm=None):
    return jnp.transpose(x, axes=perm)


@def_op("swapaxes")
def swapaxes(x, axis1=0, axis2=1):
    return jnp.swapaxes(x, axis1, axis2)


@def_op("moveaxis")
def moveaxis(x, source=0, destination=0):
    return jnp.moveaxis(x, source, destination)


@def_op("concat_op")
def _concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    """paddle.concat — takes a list/tuple of tensors."""
    if isinstance(axis, (list, tuple)):
        raise TypeError("axis must be int")
    return _concat(*x, axis=int(axis))


@def_op("stack_op")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack(*x, axis=int(axis))


@def_op("split_op")
def _split(x, num_or_sections=1, axis=0):
    if isinstance(num_or_sections, int):
        outs = jnp.split(x, num_or_sections, axis=axis)
    else:
        # sections may contain one -1 (inferred), paddle-style
        sections = list(num_or_sections)
        if -1 in sections:
            known = sum(s for s in sections if s != -1)
            sections[sections.index(-1)] = x.shape[axis] - known
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        outs = jnp.split(x, idx, axis=axis)
    return tuple(outs)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(num_or_sections, (list, tuple)):
        num_or_sections = tuple(int(s) for s in num_or_sections)
    return list(_split(x, num_or_sections=num_or_sections, axis=int(axis)))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


@def_op("unstack_op")
def _unstack(x, axis=0, num=None):
    n = num if num is not None else x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


def unstack(x, axis=0, num=None):
    return list(_unstack(x, axis=axis, num=num))


@def_op("squeeze")
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a for a in axis if x.shape[a] == 1)
    if not axis:
        return x
    return jnp.squeeze(x, axis=axis)


@def_op("unsqueeze")
def unsqueeze(x, axis=0):
    return jnp.expand_dims(x, axis)


@def_op("flatten_op")
def flatten(x, start_axis=0, stop_axis=-1):
    ndim = x.ndim
    if ndim == 0:
        return x.reshape(1)
    start = start_axis % ndim
    stop = stop_axis % ndim
    new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return x.reshape(new_shape)


@def_op("expand")
def expand(x, shape=()):
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if s in (-1,) else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


@def_op("expand_as")
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@def_op("broadcast_to")
def broadcast_to(x, shape=()):
    return jnp.broadcast_to(x, shape)


@def_op("tile")
def tile(x, repeat_times=()):
    return jnp.tile(x, repeat_times)


@def_op("repeat_interleave")
def repeat_interleave(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@def_op("roll")
def roll(x, shifts=0, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@def_op("flip")
def flip(x, axis=None):
    return jnp.flip(x, axis=axis)


@def_op("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


@def_op("cast")
def cast(x, dtype="float32"):
    from ..core.dtype import convert_dtype

    return x.astype(convert_dtype(dtype))


@def_op("assign")
def assign(x):
    return jnp.asarray(x) + 0  # force a copy-op so autograd sees identity


@def_op("slice_op")
def slice_op(x, axes=(), starts=(), ends=()):
    idx = [_pyslice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = _pyslice(st, en)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):
    return slice_op(x, axes=tuple(axes), starts=tuple(int(s) for s in starts),
                    ends=tuple(int(e) for e in ends))


@def_op("strided_slice")
def strided_slice(x, axes=(), starts=(), ends=(), strides=()):
    idx = [_pyslice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = _pyslice(st, en, sd)
    return x[tuple(idx)]


@def_op("getitem")
def _getitem(x, *index_tensors, index_spec=()):
    idx = _decode_index(index_spec, list(index_tensors))
    return x[idx]


def _encode_index(item, tensors):
    """Encode an indexing expression into a hashable spec + tensor list."""
    from ..tensor import Tensor
    import numpy as np

    if isinstance(item, tuple):
        return ("tuple", tuple(_encode_index(i, tensors) for i in item))
    if isinstance(item, Tensor):
        tensors.append(item)
        return ("t",)
    if isinstance(item, (jnp.ndarray, np.ndarray)):
        tensors.append(item)
        return ("t",)
    if isinstance(item, _pyslice):
        return ("slice", item.start, item.stop, item.step)
    if item is None:
        return ("none",)
    if item is Ellipsis:
        return ("ellipsis",)
    if isinstance(item, (list,)):
        return ("list", tuple(item))
    if isinstance(item, (int, bool)):
        return ("const", item)
    raise TypeError(f"unsupported index: {item!r}")


def _decode_index(spec, tensors):
    kind = spec[0]
    if kind == "tuple":
        return tuple(_decode_index(s, tensors) for s in spec[1])
    if kind == "t":
        return tensors.pop(0)
    if kind == "slice":
        return _pyslice(spec[1], spec[2], spec[3])
    if kind == "none":
        return None
    if kind == "ellipsis":
        return Ellipsis
    if kind == "list":
        return jnp.asarray(spec[1])
    if kind == "const":
        return spec[1]
    raise TypeError(f"bad index spec {spec}")


def getitem(x, item):
    tensors = []
    spec = _encode_index(item, tensors)
    return _getitem(x, *tensors, index_spec=spec)


@def_op("gather")
def gather(x, index, axis=0):
    index = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, index, axis=axis)


@def_op("gather_nd")
def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


@def_op("take_along_axis")
def take_along_axis(x, indices, axis=0, broadcast=True):
    return jnp.take_along_axis(x, indices, axis=axis)


@def_op("put_along_axis")
def put_along_axis(x, indices, values, axis=0, reduce="assign"):
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    if reduce in ("add", "mul", "multiply"):
        # scatter via explicit index grids (jnp.put_along_axis lacks modes)
        idx = jnp.indices(indices.shape, sparse=False)
        index_tuple = tuple(
            indices if d == (axis % x.ndim) else idx[d] for d in range(x.ndim)
        )
        values = jnp.broadcast_to(values, indices.shape)
        if reduce == "add":
            return x.at[index_tuple].add(values)
        return x.at[index_tuple].multiply(values)
    raise NotImplementedError(f"put_along_axis reduce={reduce}")


@def_op("index_select")
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@def_op("index_sample")
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@def_op("scatter")
def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@def_op("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


@def_op("masked_fill")
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@def_op("pad")
def pad(x, pad=(), mode="constant", value=0.0, data_format="NCHW"):
    pad = tuple(pad)
    if len(pad) == 2 * x.ndim:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle convention: pads apply to the last len(pad)//2 dims,
        # ordered from the last dim backward in (before, after) pairs.
        n = len(pad) // 2
        # paddle convention: pairs are ordered from the LAST dim backward
        # ([left,right,top,bottom] pads W then H on NCHW), torch-style.
        if data_format in ("NCHW", "NCL", "NCDHW") and n == x.ndim - 2:
            width = [(0, 0), (0, 0)] + [
                (pad[2 * (n - 1 - i)], pad[2 * (n - 1 - i) + 1])
                for i in range(n)
            ]
        else:
            width = [(0, 0)] * (x.ndim - n) + [
                (pad[2 * i], pad[2 * i + 1]) for i in range(n)
            ]
    mode_map = {"constant": "constant", "reflect": "reflect",
                "replicate": "edge", "circular": "wrap"}
    kwargs = {"constant_values": value} if mode == "constant" else {}
    return jnp.pad(x, width, mode=mode_map[mode], **kwargs)


@def_op("tril")
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@def_op("triu")
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@def_op("diag_embed")
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1]
    m = n + builtins.abs(offset)
    out = jnp.zeros(x.shape[:-1] + (m, m), x.dtype)
    rows = jnp.arange(n) + (0 if offset >= 0 else -offset)
    cols = jnp.arange(n) + (offset if offset >= 0 else 0)
    out = out.at[..., rows, cols].set(x)
    if (dim1 % out.ndim, dim2 % out.ndim) != (out.ndim - 2, out.ndim - 1):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


@def_op("unbind_op")
def _unbind(x, axis=0):
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, x.shape[axis], axis=axis))


def unbind(x, axis=0):
    return list(_unbind(x, axis=axis))


@def_op("one_hot", differentiable=False)
def one_hot(x, num_classes=-1):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


@def_op("unique", differentiable=False)
def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    # NOTE: dynamic-shape; eager-only.
    import numpy as np

    res = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


@def_op("numel_op")
def numel_op(x):
    return jnp.asarray(x.size, dtype=jnp.int64)


def numel(x):
    return numel_op(x)
