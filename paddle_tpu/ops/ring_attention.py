"""Ring attention: exact flash attention over a sequence-sharded ring.

The reference snapshot has NO ring attention / context parallelism
(SURVEY.md §5 long-context: only Megatron-SP + SEP topology + flash
attention; repo-wide grep confirms absence). This is the planned
superset feature: the ICI torus is a natural ring, so blockwise online-
softmax attention with k/v rotating one hop per step gives exact
attention over sequences sharded across the 'sep' mesh axis with O(S/n)
activation memory per chip and comm that overlaps the per-block matmuls
(XLA pipelines the ppermute with the einsums).

Algorithm (Liu et al. ring attention; blockwise flash accumulation):
each step computes the local q block against the currently-held k/v
block in f32 with running (max, sum, acc) statistics, then ppermutes
k/v one rank forward. Causality is applied with *global* positions, so
the result is bit-identical math to full causal attention.

Gradients come from jax.vjp through the loop (ppermute is linear; its
transpose is the reverse ppermute), so backward re-runs the ring in the
opposite direction inside the same compiled step.
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op
from ..core.enforce import enforce

_NEG = -1e30


def _ring_attention(q, k, v, axes=(), causal=True, scale=None):
    """q,k,v: [B, S_local, H, D] (seq sharded over ``axes``)."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    f32 = jnp.float32
    qf = q.astype(f32) * scale

    if not axes:
        n = 1
        idx = jnp.int32(0)
    else:
        from ..distributed import collective as C

        n = 1
        for a in axes:
            n *= C.axis_size(a)
        idx = C.axis_index(axes)

    q_pos = idx * Sq + jnp.arange(Sq)
    m = jnp.full((B, H, Sq), _NEG, f32)
    l = jnp.zeros((B, H, Sq), f32)
    acc = jnp.zeros((B, H, Sq, D), f32)
    kj, vj = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for t in range(n):
        src = (idx - t) % n  # who produced the block we now hold
        kv_pos = src * Skv + jnp.arange(Skv)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kj.astype(f32))
        if causal:
            keep = (q_pos[:, None] >= kv_pos[None, :])
            s = jnp.where(keep[None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(keep[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + \
            jnp.einsum("bhqk,bkhd->bhqd", p, vj.astype(f32))
        m = m_new
        if t < n - 1:
            from ..distributed import collective as C

            kj = C.t_ppermute(kj, axes[0], perm)
            vj = C.t_ppermute(vj, axes[0], perm)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


@def_op("ring_flash_attention")
def ring_flash_attention(q, k, v, axes=(), causal=True, scale=None):
    """Exact attention over seq-sharded q/k/v; [B, S_local, H, D] in/out."""
    return _ring_attention(q, k, v, axes=tuple(axes), causal=causal,
                           scale=scale)


def ring_attention(q, k, v, group=None, causal=True, scale=None):
    """Tensor-level entry. ``group`` defaults to the fleet sep group;
    falls back to plain attention when the ring has one rank."""
    from ..distributed import collective as C

    axes = None
    if group is not None:
        axes = group.axis_names if group.nranks > 1 else None
    else:
        from ..distributed import fleet as _fleet

        hcg = _fleet.get_hybrid_communicate_group()
        if hcg is not None and hcg.get_sep_parallel_world_size() > 1:
            axes = hcg.get_sep_parallel_group().axis_names
    if axes is None or not C.in_spmd_region():
        from .attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    enforce(len(axes) == 1, "ring attention needs a single mesh axis")
    return ring_flash_attention(q, k, v, axes=tuple(axes), causal=causal,
                                scale=scale)
