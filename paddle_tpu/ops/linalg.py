"""Linear-algebra decompositions and solvers (paddle.linalg analog).

(reference: python/paddle/tensor/linalg.py over the PHI kernels
paddle/phi/kernels/svd_kernel.h, qr_kernel.h, eigh_kernel.h,
cholesky_kernel.h, solve_kernel.h, lstsq_kernel.h, lu_kernel.h,
matrix_rank_kernel.h, pinv — declared in phi/api/yaml/ops.yaml.)

TPU-native: every factorization maps onto jax.numpy.linalg /
jax.scipy.linalg, which lower to XLA's native decomposition expansions
(QR/SVD/Eigh run as compiled blocked Householder/Jacobi programs on
TPU — no LAPACK dynload needed). Differentiable wherever JAX defines
the VJP (svd/qr/eigh/cholesky/solve/inv/det/...); general
(non-symmetric) eigendecomposition is CPU-backed in XLA and registered
non-differentiable, matching the reference's CPU-only Eig kernel.
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op
from ..core.enforce import enforce

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det",
    "eig", "eigh", "eigvals", "eigvalsh", "inv", "lstsq", "lu",
    "matrix_exp", "matrix_power", "matrix_rank", "multi_dot", "pinv",
    "qr", "slogdet", "solve", "svd", "svdvals", "triangular_solve",
    "householder_product",
]


@def_op("svd")
def svd(x, full_matrices=False):
    """Returns (U, S, VH) like the reference svd_kernel."""
    return tuple(jnp.linalg.svd(x, full_matrices=bool(full_matrices)))


@def_op("svdvals")
def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


@def_op("qr")
def qr(x, mode="reduced"):
    out = jnp.linalg.qr(x, mode=str(mode))
    return tuple(out) if isinstance(out, tuple) else out


@def_op("eigh")
def eigh(x, UPLO="L"):
    return tuple(jnp.linalg.eigh(x, UPLO=str(UPLO)))


@def_op("eigvalsh", differentiable=False)
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=str(UPLO))


@def_op("eig", differentiable=False)
def eig(x):
    """General eigendecomposition (CPU-backed in XLA, like the
    reference's CPU-only Eig kernel)."""
    return tuple(jnp.linalg.eig(x))


@def_op("eigvals", differentiable=False)
def eigvals(x):
    return jnp.linalg.eigvals(x)


@def_op("cholesky")
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@def_op("cholesky_solve")
def cholesky_solve(x, y, upper=False):
    """Solve A @ out = x given y = chol factor of A (paddle arg order)."""
    from jax.scipy.linalg import cho_solve

    return cho_solve((y, not upper), x)  # cho_solve takes `lower`


@def_op("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@def_op("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False,
                     unitriangular=False):
    return lax.linalg.triangular_solve(
        x, y, left_side=True, lower=not upper,
        transpose_a=bool(transpose), unit_diagonal=bool(unitriangular))


@def_op("lstsq")
def lstsq(x, y, rcond=None, driver=None):
    """Returns (solution, residuals, rank, singular_values) like the
    reference lstsq_kernel."""
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@def_op("lu", differentiable=False)
def lu(x, pivot=True):
    """Packed LU + pivots (reference lu_kernel; pivots are the
    lu_factor convention)."""
    enforce(pivot, "lu(pivot=False) is not supported on XLA backends")
    from jax.scipy.linalg import lu_factor

    lu_, piv = lu_factor(x)
    return lu_, piv


@def_op("det")
def det(x):
    return jnp.linalg.det(x)


@def_op("slogdet")
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


@def_op("inv")
def inv(x):
    return jnp.linalg.inv(x)


@def_op("pinv")
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=float(rcond),
                           hermitian=bool(hermitian))


@def_op("matrix_power")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, int(n))


@def_op("matrix_rank", differentiable=False)
def matrix_rank(x, tol=None, hermitian=False):
    """``tol`` is ABSOLUTE (paddle semantics) on both branches."""
    if hermitian:
        w = jnp.abs(jnp.linalg.eigvalsh(x))
    else:
        w = jnp.linalg.svd(x, compute_uv=False)
    t = tol if tol is not None else (
        jnp.max(w, -1) * max(x.shape[-2:]) * jnp.finfo(x.dtype).eps)
    return jnp.sum(w > jnp.asarray(t)[..., None], axis=-1)


@def_op("matrix_exp")
def matrix_exp(x):
    from jax.scipy.linalg import expm

    return expm(x)


@def_op("cond", differentiable=False)
def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@def_op("multi_dot")
def multi_dot(xs):
    return jnp.linalg.multi_dot(list(xs))


@def_op("cov", differentiable=False)
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=bool(rowvar), bias=not ddof,
                   fweights=fweights, aweights=aweights)


@def_op("corrcoef", differentiable=False)
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=bool(rowvar))


@def_op("householder_product")
def householder_product(x, tau):
    from jax.lax.linalg import householder_product as hp

    return hp(x, tau)
