"""Math operators (elementwise, reductions, linalg, comparison).

TPU-native kernel set covering the reference's math surface
(reference: python/paddle/tensor/math.py, tensor/linalg.py:176 matmul,
phi/kernels/{cpu,gpu}/elementwise_*).  Every kernel is a pure jnp/lax
function registered via def_op; dispatch + autograd live in
core/dispatch.py / autograd/engine.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import def_op

# ---------------------------------------------------------------------------
# Elementwise binary
# ---------------------------------------------------------------------------


@def_op("add")
def add(x, y):
    return jnp.add(x, y)


@def_op("subtract")
def subtract(x, y):
    return jnp.subtract(x, y)


@def_op("multiply")
def multiply(x, y):
    return jnp.multiply(x, y)


@def_op("divide")
def divide(x, y):
    return jnp.divide(x, y)


@def_op("floor_divide")
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@def_op("remainder")
def remainder(x, y):
    return jnp.remainder(x, y)


mod = remainder


@def_op("pow")
def pow(x, y):
    return jnp.power(x, y)


@def_op("maximum")
def maximum(x, y):
    return jnp.maximum(x, y)


@def_op("minimum")
def minimum(x, y):
    return jnp.minimum(x, y)


@def_op("fmax")
def fmax(x, y):
    return jnp.fmax(x, y)


@def_op("fmin")
def fmin(x, y):
    return jnp.fmin(x, y)


@def_op("atan2")
def atan2(x, y):
    return jnp.arctan2(x, y)


@def_op("hypot")
def hypot(x, y):
    return jnp.hypot(x, y)


# ---------------------------------------------------------------------------
# Elementwise unary
# ---------------------------------------------------------------------------


@def_op("exp")
def exp(x):
    return jnp.exp(x)


@def_op("expm1")
def expm1(x):
    return jnp.expm1(x)


@def_op("log")
def log(x):
    return jnp.log(x)


@def_op("log2")
def log2(x):
    return jnp.log2(x)


@def_op("log10")
def log10(x):
    return jnp.log10(x)


@def_op("log1p")
def log1p(x):
    return jnp.log1p(x)


@def_op("sqrt")
def sqrt(x):
    return jnp.sqrt(x)


@def_op("rsqrt")
def rsqrt(x):
    return lax.rsqrt(x)


@def_op("abs")
def abs(x):
    return jnp.abs(x)


@def_op("neg")
def neg(x):
    return jnp.negative(x)


@def_op("sign")
def sign(x):
    return jnp.sign(x)


@def_op("reciprocal")
def reciprocal(x):
    return jnp.reciprocal(x)


@def_op("square")
def square(x):
    return jnp.square(x)


@def_op("sin")
def sin(x):
    return jnp.sin(x)


@def_op("cos")
def cos(x):
    return jnp.cos(x)


@def_op("tan")
def tan(x):
    return jnp.tan(x)


@def_op("asin")
def asin(x):
    return jnp.arcsin(x)


@def_op("acos")
def acos(x):
    return jnp.arccos(x)


@def_op("atan")
def atan(x):
    return jnp.arctan(x)


@def_op("sinh")
def sinh(x):
    return jnp.sinh(x)


@def_op("cosh")
def cosh(x):
    return jnp.cosh(x)


@def_op("tanh")
def tanh(x):
    return jnp.tanh(x)


@def_op("asinh")
def asinh(x):
    return jnp.arcsinh(x)


@def_op("acosh")
def acosh(x):
    return jnp.arccosh(x)


@def_op("atanh")
def atanh(x):
    return jnp.arctanh(x)


@def_op("erf")
def erf(x):
    return lax.erf(x)


@def_op("erfinv")
def erfinv(x):
    return lax.erf_inv(x)


@def_op("floor", differentiable=False)
def floor(x):
    return jnp.floor(x)


@def_op("ceil", differentiable=False)
def ceil(x):
    return jnp.ceil(x)


@def_op("round", differentiable=False)
def round(x):
    return jnp.round(x)


@def_op("trunc", differentiable=False)
def trunc(x):
    return jnp.trunc(x)


@def_op("frac")
def frac(x):
    return x - jnp.trunc(x)


@def_op("digamma")
def digamma(x):
    return lax.digamma(x)


@def_op("lgamma")
def lgamma(x):
    return lax.lgamma(x)


@def_op("clip")
def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


@def_op("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@def_op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@def_op("logit")
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@def_op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


# ---------------------------------------------------------------------------
# Reductions
# ---------------------------------------------------------------------------


@def_op("sum")
def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=axis, dtype=dtype, keepdims=keepdim)


@def_op("mean")
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


@def_op("max")
def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


@def_op("min")
def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


@def_op("prod")
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


@def_op("logsumexp")
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


@def_op("std")
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


@def_op("var")
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


@def_op("median")
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


@def_op("all", differentiable=False)
def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


@def_op("any", differentiable=False)
def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


@def_op("amax")
def amax(x, axis=None, keepdim=False):
    return jnp.amax(x, axis=axis, keepdims=keepdim)


@def_op("amin")
def amin(x, axis=None, keepdim=False):
    return jnp.amin(x, axis=axis, keepdims=keepdim)


@def_op("cumsum")
def cumsum(x, axis=None):
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=axis)


@def_op("cumprod")
def cumprod(x, dim=None):
    if dim is None:
        return jnp.cumprod(x.reshape(-1))
    return jnp.cumprod(x, axis=dim)


@def_op("cummax", differentiable=False)
def cummax(x, axis=-1):
    return lax.cummax(x, axis=axis)


# ---------------------------------------------------------------------------
# Linear algebra
# ---------------------------------------------------------------------------


@def_op("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False):
    """Batched matmul (reference: python/paddle/tensor/linalg.py:176).

    Lowers straight to dot_general so XLA tiles it on the MXU; transposes
    fold into the contraction dims instead of materialising.
    """
    if x.ndim == 1 and y.ndim == 1:
        return jnp.dot(x, y)
    if transpose_x and x.ndim >= 2:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y and y.ndim >= 2:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


@def_op("dot")
def dot(x, y):
    if x.ndim == 2:
        return jnp.sum(x * y, axis=-1)
    return jnp.dot(x, y)


@def_op("outer")
def outer(x, y):
    return jnp.outer(x, y)


@def_op("inner")
def inner(x, y):
    return jnp.inner(x, y)


@def_op("cross")
def cross(x, y, axis=9):
    return jnp.cross(x, y, axis=axis if axis != 9 else -1)


@def_op("norm")
def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (tuple, list)) else None,
                               axis=axis, keepdims=keepdim)
    if p == float("inf") or p == "inf":
        return jnp.linalg.norm(x, ord=jnp.inf, axis=axis, keepdims=keepdim)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


@def_op("t")
def t(x):
    return x.T


@def_op("trace_op")
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@def_op("diag")
def diag(x, offset=0):
    return jnp.diag(x, k=offset)


@def_op("kron")
def kron(x, y):
    return jnp.kron(x, y)


@def_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y)


@def_op("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@def_op("einsum_op")
def _einsum(*operands, equation=""):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    return _einsum(*operands, equation=equation)


@def_op("multiply_no_broadcast")
def mv(x, vec):
    return jnp.matmul(x, vec)


# ---------------------------------------------------------------------------
# Comparison / logical (non-differentiable)
# ---------------------------------------------------------------------------


@def_op("equal", differentiable=False)
def equal(x, y):
    return jnp.equal(x, y)


@def_op("not_equal", differentiable=False)
def not_equal(x, y):
    return jnp.not_equal(x, y)


@def_op("less_than", differentiable=False)
def less_than(x, y):
    return jnp.less(x, y)


@def_op("less_equal", differentiable=False)
def less_equal(x, y):
    return jnp.less_equal(x, y)


@def_op("greater_than", differentiable=False)
def greater_than(x, y):
    return jnp.greater(x, y)


@def_op("greater_equal", differentiable=False)
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@def_op("logical_and", differentiable=False)
def logical_and(x, y):
    return jnp.logical_and(x, y)


@def_op("logical_or", differentiable=False)
def logical_or(x, y):
    return jnp.logical_or(x, y)


@def_op("logical_not", differentiable=False)
def logical_not(x):
    return jnp.logical_not(x)


@def_op("logical_xor", differentiable=False)
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@def_op("bitwise_and", differentiable=False)
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@def_op("bitwise_or", differentiable=False)
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@def_op("bitwise_xor", differentiable=False)
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@def_op("bitwise_not", differentiable=False)
def bitwise_not(x):
    return jnp.bitwise_not(x)


@def_op("isnan", differentiable=False)
def isnan(x):
    return jnp.isnan(x)


@def_op("isinf", differentiable=False)
def isinf(x):
    return jnp.isinf(x)


@def_op("isfinite", differentiable=False)
def isfinite(x):
    return jnp.isfinite(x)


@def_op("isclose", differentiable=False)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@def_op("allclose", differentiable=False)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


# ---------------------------------------------------------------------------
# Index/search ops
# ---------------------------------------------------------------------------


@def_op("argmax", differentiable=False)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(jnp.dtype(dtype))


@def_op("argmin", differentiable=False)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(jnp.dtype(dtype))


@def_op("argsort", differentiable=False)
def argsort(x, axis=-1, descending=False):
    out = jnp.argsort(x, axis=axis, descending=descending)
    return out.astype(jnp.int64)


@def_op("sort")
def sort(x, axis=-1, descending=False):
    return jnp.sort(x, axis=axis, descending=descending)


@def_op("topk")
def topk(x, k, axis=-1, largest=True, sorted=True):
    if axis != -1 and axis != x.ndim - 1:
        x_m = jnp.moveaxis(x, axis, -1)
    else:
        x_m = x
    if largest:
        vals, idx = lax.top_k(x_m, k)
    else:
        vals, idx = lax.top_k(-x_m, k)
        vals = -vals
    if axis != -1 and axis != x.ndim - 1:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


@def_op("where")
def where(condition, x, y):
    return jnp.where(condition, x, y)


@def_op("nonzero", differentiable=False)
def nonzero(x, as_tuple=False):
    # NOTE: dynamic-shape op; eager-only (not traceable under jit).
    import numpy as np

    arr = np.asarray(x)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(jnp.asarray(n) for n in nz)
    return jnp.stack([jnp.asarray(n) for n in nz], axis=1).astype(jnp.int64)


@def_op("searchsorted", differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, values, side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@def_op("bincount", differentiable=False)
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


# --- round-4 surface widening (reference ops.yaml rows) -----------------

@def_op("trace")
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=int(offset), axis1=int(axis1),
                     axis2=int(axis2))


@def_op("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=int(offset), axis1=int(axis1),
                        axis2=int(axis2))


@def_op("nansum")
def nansum(x, axis=None, keepdim=False, dtype=None):
    out = jnp.nansum(x, axis=axis, keepdims=bool(keepdim))
    return out.astype(dtype) if dtype is not None else out


@def_op("nanmean")
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=bool(keepdim))


@def_op("nanmedian", differentiable=False)
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=bool(keepdim))


@def_op("quantile", differentiable=False)
def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=axis,
                        keepdims=bool(keepdim), method=str(interpolation))


@def_op("kthvalue", differentiable=False)
def kthvalue(x, k, axis=-1, keepdim=False):
    srt = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis)
    val = jnp.take(srt, int(k) - 1, axis=axis)
    ind = jnp.take(idx, int(k) - 1, axis=axis)
    if keepdim:
        val = jnp.expand_dims(val, axis)
        ind = jnp.expand_dims(ind, axis)
    return val, ind


@def_op("mode", differentiable=False)
def mode(x, axis=-1, keepdim=False):
    import jax.scipy.stats as jst

    val, _ = jst.mode(x, axis=axis, keepdims=True)
    idx = jnp.argmax(jnp.flip(x == val, axis), axis=axis, keepdims=True)
    idx = x.shape[axis] - 1 - idx
    if not keepdim:
        val = jnp.squeeze(val, axis)
        idx = jnp.squeeze(idx, axis)
    return val, idx


@def_op("diff")
def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=int(n), axis=int(axis), prepend=prepend,
                    append=append)


@def_op("trapezoid")
def trapezoid(y, x=None, dx=None, axis=-1):
    return jnp.trapezoid(y, x=x, dx=1.0 if dx is None else float(dx),
                         axis=int(axis))


@def_op("logcumsumexp")
def logcumsumexp(x, axis=None):
    from jax import lax as _lax

    ax = -1 if axis is None else int(axis)
    xf = x if axis is not None else x.reshape(-1)
    m = jnp.max(xf, axis=ax, keepdims=True)
    out = jnp.log(jnp.cumsum(jnp.exp(xf - m), axis=ax)) + m
    return out


@def_op("logaddexp")
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@def_op("angle")
def angle(x):
    return jnp.angle(x)


@def_op("conj")
def conj(x):
    return jnp.conj(x)


@def_op("real")
def real(x):
    return jnp.real(x)


@def_op("imag")
def imag(x):
    return jnp.imag(x)


@def_op("heaviside")
def heaviside(x, y):
    return jnp.heaviside(x, y)


@def_op("copysign")
def copysign(x, y):
    return jnp.copysign(x, y)


@def_op("nextafter", differentiable=False)
def nextafter(x, y):
    return jnp.nextafter(x, y)


@def_op("ldexp")
def ldexp(x, y):
    return jnp.ldexp(x, y)


@def_op("frexp", differentiable=False)
def frexp(x):
    return jnp.frexp(x)


@def_op("i0")
def i0(x):
    return jnp.i0(x)


@def_op("igamma", differentiable=False)
def igamma(a, x):
    from jax.scipy.special import gammainc

    return gammainc(a, x)


@def_op("polygamma", differentiable=False)
def polygamma(x, n=1):
    from jax.scipy.special import polygamma as pg

    return pg(int(n), x)


@def_op("vander", differentiable=False)
def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=None if n is None else int(n),
                      increasing=bool(increasing))


@def_op("histogram", differentiable=False)
def histogram(x, bins=100, min=0, max=0, weight=None, density=False):
    rng = None if (min == 0 and max == 0) else (float(min), float(max))
    w = weight.reshape(-1) if weight is not None else None
    h, edges = jnp.histogram(x.reshape(-1), bins=int(bins), range=rng,
                             weights=w, density=bool(density))
    return h


@def_op("bucketize", differentiable=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, x,
                           side="right" if right else "left")
    return out.astype(jnp.int32) if out_int32 else out
