"""Top-level API tail (reference: python/paddle/__init__.py exports
without a previous counterpart — tensor predicates, math leftovers,
stack/split variants, scatter-into-view ops, and the ``foo_`` inplace
family generated over existing ops).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dispatch import def_op
from ..core.enforce import enforce
from ..tensor import Tensor, to_tensor

__all__ = [
    "is_tensor", "is_complex", "is_floating_point", "is_integer", "rank",
    "gcd", "lcm", "multigammaln", "nanquantile", "polar",
    "deg2rad", "rad2deg", "sgn", "signbit", "take", "tensordot",
    "tensor_split", "vsplit", "hsplit", "vstack", "hstack", "row_stack",
    "column_stack", "dstack", "scatter_nd", "select_scatter",
    "slice_scatter", "masked_scatter", "mm", "standard_normal",
    "randint_like", "unflatten", "view", "view_as", "tolist",
    "set_printoptions", "summary", "where_",
]


# ---------------------------------------------------------------------------
# predicates
# ---------------------------------------------------------------------------
def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x.dtype, jnp.integer)


def rank(input, name=None):
    return to_tensor(np.asarray(input.ndim, np.int32))


# ---------------------------------------------------------------------------
# math tail
# ---------------------------------------------------------------------------
@def_op("gcd", differentiable=False)
def gcd(x, y):
    return jnp.gcd(x, y)


@def_op("lcm", differentiable=False)
def lcm(x, y):
    return jnp.lcm(x, y)


@def_op("multigammaln")
def multigammaln(x, p):
    from jax.scipy.special import gammaln

    p = int(p)
    i = jnp.arange(1, p + 1, dtype=x.dtype)
    const = p * (p - 1) / 4.0 * jnp.log(jnp.asarray(jnp.pi, x.dtype))
    return const + jnp.sum(gammaln(x[..., None] + (1.0 - i) / 2.0),
                           axis=-1)


@def_op("nanquantile")
def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, q, axis=axis, keepdims=bool(keepdim))


@def_op("polar")
def polar(abs, angle):  # noqa: A002
    from jax import lax

    return lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


@def_op("deg2rad")
def deg2rad(x):
    return jnp.deg2rad(x)


@def_op("rad2deg")
def rad2deg(x):
    return jnp.rad2deg(x)


@def_op("sgn")
def sgn(x):
    """sign for real; x/|x| (unit phasor, 0 at 0) for complex."""
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0.0 + 0.0j,
                         x / jnp.where(mag == 0, 1.0, mag))
    return jnp.sign(x)


@def_op("signbit", differentiable=False)
def signbit(x):
    return jnp.signbit(x)


@def_op("take")
def take(x, index, mode="raise"):
    """Flat-index gather (reference: tensor/math.py take): 'raise'
    wraps negatives python-style, 'wrap' is modular, 'clip' clamps to
    [0, n-1] (negatives go to 0, numpy semantics)."""
    enforce(mode in ("raise", "wrap", "clip"),
            lambda: f"take mode must be raise/wrap/clip, got {mode!r}")
    flat = x.reshape(-1)
    n = flat.shape[0]
    idx = index.astype(jnp.int32)
    if mode == "wrap":
        idx = idx % n
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    else:  # 'raise': python-style negatives; cannot raise inside a
        # traced program, so out-of-range clamps (documented)
        idx = jnp.where(idx < 0, idx + n, idx)
        idx = jnp.clip(idx, 0, n - 1)
    return flat[idx]


@def_op("tensordot")
def tensordot(x, y, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                     for a in axes)
    return jnp.tensordot(x, y, axes=axes)


def tensor_split(x, num_or_indices, axis=0, name=None):
    from .manipulation import split

    ax = int(axis)
    n = x.shape[ax]
    if np.isscalar(num_or_indices):
        k = int(num_or_indices)
        # numpy semantics: first n % k chunks get one extra element
        base, extra = divmod(n, k)
        sizes = [base + 1] * extra + [base] * (k - extra)
        return split(x, sizes, axis=ax)
    # numpy semantics: negative indices count from the end, out-of-
    # range clips (possibly yielding empty chunks)
    norm = [min(max(int(i) + n, 0) if int(i) < 0 else min(int(i), n), n)
            for i in num_or_indices]
    idx = [0] + norm + [n]
    sizes = [max(b - a, 0) for a, b in zip(idx[:-1], idx[1:])]
    return split(x, sizes, axis=ax)


def vsplit(x, num_or_indices, name=None):
    enforce(x.ndim >= 2, "vsplit expects rank >= 2")
    return tensor_split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    enforce(x.ndim >= 1, "hsplit expects rank >= 1")
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


@def_op("vstack_op")
def _vstack(*xs):
    return jnp.vstack(xs)


def vstack(inputs, name=None):
    return _vstack(*inputs)


row_stack = vstack


@def_op("hstack_op")
def _hstack(*xs):
    return jnp.hstack(xs)


def hstack(inputs, name=None):
    return _hstack(*inputs)


@def_op("column_stack_op")
def _column_stack(*xs):
    return jnp.column_stack(xs)


def column_stack(inputs, name=None):
    return _column_stack(*inputs)


@def_op("dstack_op")
def _dstack(*xs):
    return jnp.dstack(xs)


def dstack(inputs, name=None):
    return _dstack(*inputs)


@def_op("scatter_nd")
def scatter_nd(index, updates, shape):
    out = jnp.zeros(tuple(int(s) for s in shape), updates.dtype)
    return out.at[tuple(index[..., i] for i in range(index.shape[-1]))] \
        .add(updates)


@def_op("select_scatter")
def select_scatter(x, values, axis, index):
    idx = [slice(None)] * x.ndim
    idx[int(axis)] = int(index)
    return x.at[tuple(idx)].set(values)


@def_op("slice_scatter")
def slice_scatter(x, value, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[int(ax)] = slice(int(st), int(en), int(sd))
    return x.at[tuple(idx)].set(value)


@def_op("masked_scatter_op")
def _masked_scatter(x, mask, value):
    """Fill True positions of mask with consecutive elements of value
    (reference: tensor/manipulation.py masked_scatter). Static-shape
    form: position k in row-major order takes value.flat[#True before
    k]."""
    m = jnp.broadcast_to(mask, x.shape).reshape(-1)
    xf = x.reshape(-1)
    vf = value.reshape(-1)
    pos = jnp.cumsum(m.astype(jnp.int32)) - 1
    gathered = vf[jnp.clip(pos, 0, vf.shape[0] - 1)]
    return jnp.where(m, gathered, xf).reshape(x.shape)


def masked_scatter(x, mask, value, name=None):
    import jax as _jax

    mv = mask._value if isinstance(mask, Tensor) else mask
    vv = value._value if isinstance(value, Tensor) else value
    if not isinstance(mv, _jax.core.Tracer):  # eager: validate like paddle
        need = int(np.asarray(mv).sum())
        have = int(np.prod(np.asarray(vv).shape))
        enforce(have >= need,
                lambda: f"masked_scatter needs value.numel ({have}) >= "
                        f"mask True count ({need})")
    return _masked_scatter(x, mask, value)


def mm(input, mat2, name=None):
    from .math import matmul

    return matmul(input, mat2)


def standard_normal(shape, dtype=None, name=None):
    from .creation import randn

    return randn(shape, dtype=dtype)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    """Uniform integers with x's shape; dtype defaults to x.dtype
    (floating dtypes receive integer VALUES cast to that dtype, the
    reference behavior)."""
    from .creation import randint

    target = str(dtype) if dtype is not None else str(x.dtype)
    if jnp.issubdtype(jnp.dtype(target), jnp.integer):
        return randint(low, high, shape=tuple(x.shape), dtype=target)
    return randint(low, high, shape=tuple(x.shape),
                   dtype="int32").astype(target)


def unflatten(x, axis, shape, name=None):
    from .manipulation import reshape

    shp = x.shape
    ax = int(axis) % len(shp)
    return reshape(x, list(shp[:ax]) + list(shape) + list(shp[ax + 1:]))


def view(x, shape_or_dtype, name=None):
    """Zero-copy reinterpret (reference: tensor/manipulation.py view):
    a shape view is reshape; a dtype view reinterprets the bytes."""
    from .manipulation import reshape

    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, list(shape_or_dtype))
    return _view_dtype(x, str(shape_or_dtype))


@def_op("view_dtype")
def _view_dtype(x, dtype):
    from ..core.dtype import convert_dtype

    return x.view(convert_dtype(dtype))


def view_as(x, other, name=None):
    from .manipulation import reshape

    return reshape(x, other.shape)


def tolist(x):
    return x.tolist() if isinstance(x, Tensor) \
        else np.asarray(x).tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """(reference: tensor/to_string.py set_printoptions) — numpy's
    printer renders Tensor reprs here, so forward to it."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def summary(net, input_size=None, dtypes=None, input=None):
    """Layer-table summary (reference: python/paddle/hapi/
    model_summary.py summary): prints per-layer output shapes and
    parameter counts from a dry forward."""
    rows = []
    hooks = []

    def mk_hook(name, layer):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) \
                else outputs
            shape = list(getattr(out, "shape", [])) \
                if hasattr(out, "shape") else "?"
            n_params = sum(
                int(np.prod(p.shape))
                for p in layer._parameters.values() if p is not None)
            rows.append((name, type(layer).__name__, shape, n_params,
                         id(layer)))
        return hook

    for name, sub in net.named_sublayers():
        hooks.append(sub.register_forward_post_hook(mk_hook(name, sub)))
    try:
        if input is None:
            enforce(input_size is not None,
                    "summary needs input_size or input")
            sizes = input_size if isinstance(input_size, list) \
                else [input_size]
            dts = dtypes or ["float32"] * len(sizes)
            args = [to_tensor(np.zeros(s, dt))
                    for s, dt in zip(sizes, dts)]
            net(*args)
        else:
            net(input)
    finally:
        for h in hooks:
            h.remove()
    # count each layer INSTANCE once (hooks fire per call; weight
    # sharing must not double-count)
    seen_ids = set()
    total = 0
    for name, typ, shape, n, lid in rows:
        if lid not in seen_ids:
            seen_ids.add(lid)
            total += n
    lines = [f"{'Layer':<30}{'Type':<22}{'Output shape':<20}{'Params':>10}"]
    lines.append("-" * 82)
    for name, typ, shape, n, _lid in rows:
        lines.append(f"{name:<30}{typ:<22}{str(shape):<20}{n:>10}")
    lines.append("-" * 82)
    lines.append(f"Total params: {total:,}")
    out = "\n".join(lines)
    print(out)
    return {"total_params": total, "layers": len(rows)}


# ---------------------------------------------------------------------------
# the foo_ inplace family: generated over existing public ops with the
# same value-swap contract as tensor_methods._make_inplace
# ---------------------------------------------------------------------------
def where_(condition, x, y, name=None):
    """In-place where: writes the selected values INTO x (reference:
    tensor/search.py where_ — the generic generator would wrongly
    mutate the condition argument)."""
    from ..tensor import inplace_swap
    from .math import where as _where

    return inplace_swap(x, _where(condition, x, y))


def _gen_inplace():
    from . import creation, extra, manipulation, math as math_ops

    from ..tensor import inplace_swap

    def make(fn):
        def inplace(x, *args, **kwargs):
            return inplace_swap(x, fn(x, *args, **kwargs))
        return inplace

    import sys

    mod = sys.modules[__name__]
    sources = {}
    for m in (math_ops, manipulation, extra, creation, mod):
        for n in dir(m):
            if not n.startswith("_") and callable(getattr(m, n)):
                sources.setdefault(n, getattr(m, n))
    names = [
        "lcm", "ldexp", "less_equal", "less_than", "lgamma", "log10",
        "log1p", "log2", "log", "logical_and", "logical_not",
        "logical_or", "logical_xor", "logit", "masked_fill", "mod",
        "multiply", "nan_to_num", "neg", "not_equal", "polygamma",
        "pow", "remainder", "renorm", "reshape", "scatter", "sin",
        "sinh", "square", "squeeze", "t", "tan", "tril", "triu",
        "trunc", "unsqueeze", "masked_scatter", "gcd",
    ]
    made = []
    for n in names:
        if n in sources:
            setattr(mod, n + "_", make(sources[n]))
            made.append(n + "_")
    mod.__all__ = list(mod.__all__) + made


_gen_inplace()
