"""Top-level API tail (reference: python/paddle/__init__.py exports
without a previous counterpart — tensor predicates, math leftovers,
stack/split variants, scatter-into-view ops, and the ``foo_`` inplace
family generated over existing ops).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dispatch import def_op
from ..core.enforce import enforce
from ..tensor import Tensor, to_tensor

__all__ = [
    "is_tensor", "is_complex", "is_floating_point", "is_integer", "rank",
    "gcd", "lcm", "multigammaln", "nanquantile", "polar",
    "deg2rad", "rad2deg", "sgn", "signbit", "take", "tensordot",
    "tensor_split", "vsplit", "hsplit", "vstack", "hstack", "row_stack",
    "column_stack", "dstack", "scatter_nd", "select_scatter",
    "slice_scatter", "masked_scatter", "mm", "standard_normal",
    "randint_like", "unflatten", "view", "view_as", "tolist",
    "set_printoptions", "summary", "where_",
]


# ---------------------------------------------------------------------------
# predicates
# ---------------------------------------------------------------------------
def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x.dtype, jnp.integer)


def rank(input, name=None):
    return to_tensor(np.asarray(input.ndim, np.int32))


# ---------------------------------------------------------------------------
# math tail
# ---------------------------------------------------------------------------
@def_op("gcd", differentiable=False)
def gcd(x, y):
    return jnp.gcd(x, y)


@def_op("lcm", differentiable=False)
def lcm(x, y):
    return jnp.lcm(x, y)


@def_op("multigammaln")
def multigammaln(x, p):
    from jax.scipy.special import gammaln

    p = int(p)
    i = jnp.arange(1, p + 1, dtype=x.dtype)
    const = p * (p - 1) / 4.0 * jnp.log(jnp.asarray(jnp.pi, x.dtype))
    return const + jnp.sum(gammaln(x[..., None] + (1.0 - i) / 2.0),
                           axis=-1)


@def_op("nanquantile")
def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, q, axis=axis, keepdims=bool(keepdim))


@def_op("polar")
def polar(abs, angle):  # noqa: A002
    from jax import lax

    return lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


@def_op("deg2rad")
def deg2rad(x):
    return jnp.deg2rad(x)


@def_op("rad2deg")
def rad2deg(x):
    return jnp.rad2deg(x)


@def_op("sgn")
def sgn(x):
    """sign for real; x/|x| (unit phasor, 0 at 0) for complex."""
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0.0 + 0.0j,
                         x / jnp.where(mag == 0, 1.0, mag))
    return jnp.sign(x)


@def_op("signbit", differentiable=False)
def signbit(x):
    return jnp.signbit(x)


@def_op("take")
def _take_op(x, index, mode="raise"):
    flat = x.reshape(-1)
    n = flat.shape[0]
    idx = index.astype(jnp.int32)
    if mode == "wrap":
        idx = idx % n
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    else:  # 'raise': python-style negatives; under tracing a raise is
        # impossible, so out-of-range clamps (validated eagerly below)
        idx = jnp.where(idx < 0, idx + n, idx)
        idx = jnp.clip(idx, 0, n - 1)
    return flat[idx]


def take(x, index, mode="raise", name=None):
    """Flat-index gather (reference: tensor/math.py take): 'raise'
    errors on out-of-range (negatives python-style), 'wrap' is modular,
    'clip' clamps to [0, n-1] (negatives go to 0, numpy semantics).

    The 'raise' bounds check runs HERE, pre-dispatch: the kernel body
    executes under vjp tracing even eagerly, where values are abstract
    and a data-dependent raise is impossible.
    """
    enforce(mode in ("raise", "wrap", "clip"),
            lambda: f"take mode must be raise/wrap/clip, got {mode!r}")
    if mode == "raise":
        xs = x.shape if not isinstance(x, Tensor) else x._value.shape
        n = 1
        for s in xs:
            n *= int(s)
        iv = index._value if isinstance(index, Tensor) else index
        import jax

        if not isinstance(iv, jax.core.Tracer):
            ia = np.asarray(iv)
            enforce(not bool(((ia < -n) | (ia >= n)).any()),
                    lambda: "take(mode='raise'): index out of range "
                            f"for tensor of {n} elements")
    return _take_op(x, index, mode)


@def_op("tensordot")
def tensordot(x, y, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                     for a in axes)
    return jnp.tensordot(x, y, axes=axes)


def tensor_split(x, num_or_indices, axis=0, name=None):
    from .manipulation import split

    ax = int(axis)
    n = x.shape[ax]
    if np.isscalar(num_or_indices):
        k = int(num_or_indices)
        # numpy semantics: first n % k chunks get one extra element
        base, extra = divmod(n, k)
        sizes = [base + 1] * extra + [base] * (k - extra)
        return split(x, sizes, axis=ax)
    # numpy semantics: negative indices count from the end, out-of-
    # range clips (possibly yielding empty chunks)
    norm = [min(max(int(i) + n, 0) if int(i) < 0 else min(int(i), n), n)
            for i in num_or_indices]
    idx = [0] + norm + [n]
    sizes = [max(b - a, 0) for a, b in zip(idx[:-1], idx[1:])]
    return split(x, sizes, axis=ax)


def vsplit(x, num_or_indices, name=None):
    enforce(x.ndim >= 2, "vsplit expects rank >= 2")
    return tensor_split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    enforce(x.ndim >= 1, "hsplit expects rank >= 1")
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


@def_op("vstack_op")
def _vstack(*xs):
    return jnp.vstack(xs)


def vstack(inputs, name=None):
    return _vstack(*inputs)


row_stack = vstack


@def_op("hstack_op")
def _hstack(*xs):
    return jnp.hstack(xs)


def hstack(inputs, name=None):
    return _hstack(*inputs)


@def_op("column_stack_op")
def _column_stack(*xs):
    return jnp.column_stack(xs)


def column_stack(inputs, name=None):
    return _column_stack(*inputs)


@def_op("dstack_op")
def _dstack(*xs):
    return jnp.dstack(xs)


def dstack(inputs, name=None):
    return _dstack(*inputs)


@def_op("scatter_nd")
def scatter_nd(index, updates, shape):
    out = jnp.zeros(tuple(int(s) for s in shape), updates.dtype)
    return out.at[tuple(index[..., i] for i in range(index.shape[-1]))] \
        .add(updates)


@def_op("select_scatter")
def select_scatter(x, values, axis, index):
    idx = [slice(None)] * x.ndim
    idx[int(axis)] = int(index)
    return x.at[tuple(idx)].set(values)


@def_op("slice_scatter")
def slice_scatter(x, value, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[int(ax)] = slice(int(st), int(en), int(sd))
    return x.at[tuple(idx)].set(value)


@def_op("masked_scatter_op")
def _masked_scatter(x, mask, value):
    """Fill True positions of mask with consecutive elements of value
    (reference: tensor/manipulation.py masked_scatter). Static-shape
    form: position k in row-major order takes value.flat[#True before
    k]."""
    m = jnp.broadcast_to(mask, x.shape).reshape(-1)
    xf = x.reshape(-1)
    vf = value.reshape(-1)
    pos = jnp.cumsum(m.astype(jnp.int32)) - 1
    gathered = vf[jnp.clip(pos, 0, vf.shape[0] - 1)]
    return jnp.where(m, gathered, xf).reshape(x.shape)


def masked_scatter(x, mask, value, name=None):
    import jax as _jax

    mv = mask._value if isinstance(mask, Tensor) else mask
    vv = value._value if isinstance(value, Tensor) else value
    if not isinstance(mv, _jax.core.Tracer):  # eager: validate like paddle
        need = int(np.asarray(mv).sum())
        have = int(np.prod(np.asarray(vv).shape))
        enforce(have >= need,
                lambda: f"masked_scatter needs value.numel ({have}) >= "
                        f"mask True count ({need})")
    return _masked_scatter(x, mask, value)


def mm(input, mat2, name=None):
    from .math import matmul

    return matmul(input, mat2)


def standard_normal(shape, dtype=None, name=None):
    from .creation import randn

    return randn(shape, dtype=dtype)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    """Uniform integers with x's shape; dtype defaults to x.dtype
    (floating dtypes receive integer VALUES cast to that dtype, the
    reference behavior)."""
    from .creation import randint

    target = str(dtype) if dtype is not None else str(x.dtype)
    if jnp.issubdtype(jnp.dtype(target), jnp.integer):
        return randint(low, high, shape=tuple(x.shape), dtype=target)
    return randint(low, high, shape=tuple(x.shape),
                   dtype="int32").astype(target)


def unflatten(x, axis, shape, name=None):
    from .manipulation import reshape

    shp = x.shape
    ax = int(axis) % len(shp)
    return reshape(x, list(shp[:ax]) + list(shape) + list(shp[ax + 1:]))


def view(x, shape_or_dtype, name=None):
    """Zero-copy reinterpret (reference: tensor/manipulation.py view):
    a shape view is reshape; a dtype view reinterprets the bytes."""
    from .manipulation import reshape

    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, list(shape_or_dtype))
    return _view_dtype(x, str(shape_or_dtype))


@def_op("view_dtype")
def _view_dtype(x, dtype):
    from ..core.dtype import convert_dtype

    return x.view(convert_dtype(dtype))


def view_as(x, other, name=None):
    from .manipulation import reshape

    return reshape(x, other.shape)


def tolist(x):
    return x.tolist() if isinstance(x, Tensor) \
        else np.asarray(x).tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """(reference: tensor/to_string.py set_printoptions) — numpy's
    printer renders Tensor reprs here, so forward to it."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def summary(net, input_size=None, dtypes=None, input=None):
    """Layer-table summary (reference: python/paddle/hapi/
    model_summary.py summary): prints per-layer output shapes and
    parameter counts from a dry forward."""
    rows = []
    hooks = []

    def mk_hook(name, layer):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) \
                else outputs
            shape = list(getattr(out, "shape", [])) \
                if hasattr(out, "shape") else "?"
            n_params = sum(
                int(np.prod(p.shape))
                for p in layer._parameters.values() if p is not None)
            rows.append((name, type(layer).__name__, shape, n_params,
                         id(layer)))
        return hook

    for name, sub in net.named_sublayers():
        hooks.append(sub.register_forward_post_hook(mk_hook(name, sub)))
    try:
        if input is None:
            enforce(input_size is not None,
                    "summary needs input_size or input")
            sizes = input_size if isinstance(input_size, list) \
                else [input_size]
            dts = dtypes or ["float32"] * len(sizes)
            args = [to_tensor(np.zeros(s, dt))
                    for s, dt in zip(sizes, dts)]
            net(*args)
        else:
            net(input)
    finally:
        for h in hooks:
            h.remove()
    # count each layer INSTANCE once (hooks fire per call; weight
    # sharing must not double-count)
    seen_ids = set()
    total = 0
    for name, typ, shape, n, lid in rows:
        if lid not in seen_ids:
            seen_ids.add(lid)
            total += n
    lines = [f"{'Layer':<30}{'Type':<22}{'Output shape':<20}{'Params':>10}"]
    lines.append("-" * 82)
    for name, typ, shape, n, _lid in rows:
        lines.append(f"{name:<30}{typ:<22}{str(shape):<20}{n:>10}")
    lines.append("-" * 82)
    lines.append(f"Total params: {total:,}")
    out = "\n".join(lines)
    print(out)
    return {"total_params": total, "layers": len(rows)}


# ---------------------------------------------------------------------------
# the foo_ inplace family: generated over existing public ops with the
# same value-swap contract as tensor_methods._make_inplace
# ---------------------------------------------------------------------------
def where_(condition, x, y, name=None):
    """In-place where: writes the selected values INTO x (reference:
    tensor/search.py where_ — the generic generator would wrongly
    mutate the condition argument)."""
    from ..tensor import inplace_swap
    from .math import where as _where

    return inplace_swap(x, _where(condition, x, y))


def _gen_inplace():
    from . import creation, extra, manipulation, math as math_ops, \
        nn_ops

    from ..tensor import inplace_swap

    def make(fn):
        def inplace(x, *args, **kwargs):
            return inplace_swap(x, fn(x, *args, **kwargs))
        return inplace

    import sys

    mod = sys.modules[__name__]
    sources = {}
    for m in (math_ops, manipulation, extra, creation, nn_ops, mod):
        for n in dir(m):
            if not n.startswith("_") and callable(getattr(m, n)):
                sources.setdefault(n, getattr(m, n))
    names = [
        "lcm", "ldexp", "less_equal", "less_than", "lgamma", "log10",
        "log1p", "log2", "log", "logical_and", "logical_not",
        "logical_or", "logical_xor", "logit", "masked_fill", "mod",
        "multiply", "nan_to_num", "neg", "not_equal", "polygamma",
        "pow", "remainder", "renorm", "reshape", "scatter", "sin",
        "sinh", "square", "squeeze", "t", "tan", "tril", "triu",
        "trunc", "unsqueeze", "masked_scatter", "gcd", "tanh", "abs",
        "acos", "acosh", "asin", "asinh", "atan", "atanh",
        "bitwise_and", "bitwise_not", "bitwise_or", "bitwise_xor",
        "bitwise_left_shift", "bitwise_right_shift", "addmm", "add_n",
        "cast", "ceil", "copysign", "cos", "cosh", "cumprod", "cumsum",
        "digamma", "equal", "erfinv", "flatten", "floor",
        "floor_divide", "floor_mod", "frac", "gammainc", "gammaincc",
        "gammaln", "greater_equal", "greater_than", "hypot", "i0",
        "index_fill", "index_put", "lerp", "multigammaln",
        "put_along_axis", "reciprocal", "round", "rsqrt", "sigmoid",
        "transpose",
    ]
    made = []
    for n in names:
        if n in sources:
            setattr(mod, n + "_", make(sources[n]))
            made.append(n + "_")
    mod.__all__ = list(mod.__all__) + made


# ---------------------------------------------------------------------------
# second tail batch: reference Tensor-method names with no function yet
# ---------------------------------------------------------------------------
def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@def_op("cdist")
def cdist(x, y, p=2.0):
    """Pairwise distances between row sets: [..., M, D] x [..., N, D]
    -> [..., M, N] (reference: tensor/linalg.py cdist)."""
    d = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 1e-30))
    return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)


@def_op("count_nonzero", differentiable=False)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.sum((x != 0), axis=axis, keepdims=bool(keepdim))


@def_op("cumulative_trapezoid")
def cumulative_trapezoid(y, x=None, dx=1.0, axis=-1):
    axis = int(axis) % y.ndim
    sl0 = [slice(None)] * y.ndim
    sl1 = [slice(None)] * y.ndim
    sl0[axis] = slice(None, -1)
    sl1[axis] = slice(1, None)
    mid = (y[tuple(sl0)] + y[tuple(sl1)]) / 2.0
    if x is not None:
        step = jnp.diff(x, axis=axis) if x.ndim == y.ndim \
            else jnp.diff(x).reshape(
                (1,) * axis + (-1,) + (1,) * (y.ndim - axis - 1))
        mid = mid * step
    else:
        mid = mid * dx
    return jnp.cumsum(mid, axis=axis)


@def_op("diagonal_scatter")
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    enforce(x.ndim == 2 and int(axis1) == 0 and int(axis2) == 1,
            "diagonal_scatter here supports 2-D (axis1=0, axis2=1)")
    m, ncol = x.shape
    off = int(offset)
    # rectangular-correct diagonal length
    n = max(min(m + min(off, 0), ncol - max(off, 0)), 0)
    ii = jnp.arange(n)
    rows = ii - min(off, 0)
    cols = ii + max(off, 0)
    return x.at[rows, cols].set(y)


def dsplit(x, num_or_indices, name=None):
    enforce(x.ndim >= 3, "dsplit expects rank >= 3")
    return tensor_split(x, num_or_indices, axis=2)


def floor_mod(x, y, name=None):
    from .math import mod

    return mod(x, y)


def gammainc(x, y, name=None):
    from .math import igamma

    return igamma(x, y)


@def_op("histogramdd_op", differentiable=False)
def _histogramdd(x, bins=10, ranges=None, density=False, weights=None):
    h, edges = jnp.histogramdd(x, bins=bins, range=ranges,
                               density=bool(density), weights=weights)
    return (h,) + tuple(edges)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """(reference: tensor/linalg.py histogramdd) -> (hist, edges_list)."""
    out = _histogramdd(x, bins, ranges, density, weights)
    return out[0], list(out[1:])


@def_op("index_fill")
def index_fill(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[int(axis)] = index
    return x.at[tuple(idx)].set(jnp.asarray(value, x.dtype))


def inverse(x, name=None):
    from .linalg import inv

    return inv(x)


@def_op("lu_unpack")
def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True):
    """Unpack jax/LAPACK-style packed LU (reference: lu_unpack op).
    2-D only; returns (P, L, U) with identity placeholders when a
    component's unpack flag is off."""
    enforce(lu_data.ndim == 2,
            "lu_unpack here supports unbatched 2-D input")
    m, n = lu_data.shape[-2:]
    k = min(m, n)
    L = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_data.dtype)
    U = jnp.triu(lu_data[..., :k, :])
    # pivots (1-based sequential row swaps) -> permutation matrix
    piv = lu_pivots.astype(jnp.int32) - 1
    perm = jnp.arange(m)
    for i in range(piv.shape[-1]):
        j = piv[..., i]
        pi, pj = perm[i], perm[j]
        perm = perm.at[i].set(pj).at[j].set(pi)
    P = jnp.eye(m, dtype=lu_data.dtype)[perm].T
    if not unpack_ludata:
        L = jnp.eye(m, k, dtype=lu_data.dtype)
        U = jnp.eye(k, n, dtype=lu_data.dtype)
    if not unpack_pivots:
        P = jnp.eye(m, dtype=lu_data.dtype)
    return P, L, U


def sigmoid(x, name=None):
    from .nn_ops import sigmoid as _sig

    return _sig(x)


@def_op("tensor_unfold")
def tensor_unfold(x, axis, size, step):
    """Sliding windows along ``axis`` (reference: Tensor.unfold —
    DIFFERENT from nn.functional.unfold/im2col): appends a window dim."""
    axis = int(axis) % x.ndim
    size, step = int(size), int(step)
    n = (x.shape[axis] - size) // step + 1
    idx = (np.arange(n)[:, None] * step
           + np.arange(size)[None, :])           # [n, size]
    out = jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=axis)
    new_shape = (x.shape[:axis] + (n, size) + x.shape[axis + 1:])
    out = out.reshape(new_shape)
    # paddle places the window dim LAST
    return jnp.moveaxis(out, axis + 1, -1)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (reference: tensor/linalg.py pca_lowrank)."""
    from ..core import rng as _rng
    import jax as _jax

    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    m, n = xv.shape[-2:]
    q = q or min(6, m, n)
    if center:
        xv = xv - xv.mean(axis=-2, keepdims=True)
    # randomized range finder + SVD of the projected matrix
    omega = _jax.random.normal(_rng.get_key(), xv.shape[:-2] + (n, q),
                               xv.dtype)
    y = xv @ omega
    for _ in range(int(niter)):
        y = xv @ (xv.swapaxes(-1, -2) @ y)
    qmat, _ = jnp.linalg.qr(y)
    b = qmat.swapaxes(-1, -2) @ xv
    u_b, s, vT = jnp.linalg.svd(b, full_matrices=False)
    u = qmat @ u_b
    return Tensor(u), Tensor(s), Tensor(vT.swapaxes(-1, -2))


def _random_fill(x, val):
    """Route the in-place random fills through the foo_ contract: the
    fresh value has NO producer, so the stale _grad_node/_out_idx from a
    previous tracked op must be cleared (an autograd consistency bug
    otherwise: backward through x would use the old producer with the
    new value)."""
    from ..tensor import Tensor, inplace_swap

    return inplace_swap(x, Tensor(val.astype(x._value.dtype)))


def normal_(x, mean=0.0, std=1.0, name=None):
    import jax as _jax

    from ..core import rng as _rng

    return _random_fill(x, mean + std * _jax.random.normal(
        _rng.get_key(), tuple(x.shape)))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    """seed=0 (the reference default) draws from the global generator;
    a non-zero seed gives a deterministic per-call stream (reference:
    uniform_'s seed attribute on the kernel)."""
    import jax as _jax

    from ..core import rng as _rng

    key = _jax.random.PRNGKey(seed) if seed else _rng.get_key()
    return _random_fill(x, _jax.random.uniform(
        key, tuple(x.shape), minval=min, maxval=max))


def cauchy_(x, loc=0.0, scale=1.0, name=None):
    import jax as _jax

    from ..core import rng as _rng

    return _random_fill(x, loc + scale * _jax.random.cauchy(
        _rng.get_key(), tuple(x.shape)))


def geometric_(x, probs, name=None):
    import jax as _jax

    from ..core import rng as _rng

    # reference geometric_ (creation.py:2911) fills the CONTINUOUS
    # value log(u)/log1p(-p) without flooring
    u = _jax.random.uniform(_rng.get_key(), tuple(x.shape), minval=1e-20)
    return _random_fill(x, jnp.log(u) / jnp.log1p(-probs))


__all__ = list(__all__) + [
    "broadcast_shape", "cdist", "count_nonzero", "cumulative_trapezoid",
    "diagonal_scatter", "dsplit", "floor_mod", "gammainc", "histogramdd",
    "index_fill", "inverse", "lu_unpack", "sigmoid", "tensor_unfold",
    "pca_lowrank", "normal_", "uniform_", "cauchy_", "geometric_",
]


@def_op("add_n_op")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (reference: tensor/math.py
    add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    return _add_n(*inputs)


@def_op("atleast_nd")
def _atleast(x, nd):
    """Reference placement (manipulation.py atleast_*): 1d: scalars ->
    (1,); 2d: (N,) -> (1, N); 3d: (N,) -> (1, N, 1), (M, N) ->
    (M, N, 1)."""
    if nd == 1:
        return x.reshape(1) if x.ndim == 0 else x
    if nd == 2:
        if x.ndim == 0:
            return x.reshape(1, 1)
        if x.ndim == 1:
            return x[None, :]
        return x
    # nd == 3
    if x.ndim == 0:
        return x.reshape(1, 1, 1)
    if x.ndim == 1:
        return x[None, :, None]
    if x.ndim == 2:
        return x[:, :, None]
    return x


def atleast_1d(*inputs, name=None):
    out = [_atleast(x, 1) for x in inputs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*inputs, name=None):
    out = [_atleast(x, 2) for x in inputs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*inputs, name=None):
    out = [_atleast(x, 3) for x in inputs]
    return out[0] if len(out) == 1 else out


@def_op("as_strided")
def as_strided(x, shape, stride, offset=0):
    """Strided view (reference: tensor/manipulation.py as_strided) —
    expressed as a flat gather with the given element strides."""
    idx = np.zeros(tuple(int(s) for s in shape), np.int64) + int(offset)
    for d, (sz, st) in enumerate(zip(shape, stride)):
        ar = np.arange(int(sz)) * int(st)
        idx = idx + ar.reshape((1,) * d + (-1,)
                               + (1,) * (len(shape) - d - 1))
    return x.reshape(-1)[jnp.asarray(idx)]


# persistable only means something to the static-graph executor's scope
# reuse; the reference's dygraph path ignores it identically.
# tpulint: disable=unused-knob
def create_tensor(dtype, name=None, persistable=False):
    """(reference: tensor/creation.py create_tensor — a typed empty
    slot in static graphs; eagerly, an empty tensor.)"""
    from ..core.dtype import convert_dtype

    return Tensor(jnp.zeros((0,), convert_dtype(dtype)))


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """(reference: tensor/creation.py create_parameter — LayerHelper
    semantics: a ParamAttr initializer wins, then the explicit
    default_initializer, then zeros for biases / a small normal for
    weights; attr=False yields no parameter)."""
    from ..core.dtype import convert_dtype
    from ..core import rng as _rng
    from ..framework.param_attr import ParamAttr
    from ..tensor import Parameter
    import jax as _jax

    init = default_initializer
    trainable = True
    if attr is False:
        return None
    if isinstance(attr, ParamAttr):
        if attr.initializer is not None:
            init = attr.initializer
        trainable = attr.trainable
    if init is not None:
        val = jnp.asarray(init(tuple(shape), dtype))
    elif is_bias:
        val = jnp.zeros(tuple(shape))
    else:
        val = 0.02 * _jax.random.normal(_rng.get_key(), tuple(shape))
    p = Parameter(val.astype(convert_dtype(dtype)))
    p.stop_gradient = not trainable
    return p


__all__ = list(__all__) + ["add_n", "atleast_1d", "atleast_2d",
                           "atleast_3d", "as_strided", "create_tensor",
                           "create_parameter"]

_gen_inplace()
