"""Global FLAGS registry with environment override.

TPU-native re-design of the reference's gflags-compatible flag system
(reference: paddle/common/flags.h:373 ``PHI_DEFINE_EXPORTED_*``,
paddle/common/flags.cc ~139 flag definitions, exported to Python as
``paddle.set_flags`` / ``paddle.get_flags``).

Flags are process-global, typed, and overridable via ``FLAGS_<name>``
environment variables at definition time (matching the reference's
``PHI_DEFINE_EXPORTED_*`` env-export semantics).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, List, Union

__all__ = ["define_flag", "set_flags", "get_flags", "flag_defined"]

_lock = threading.Lock()


class _Flag:
    __slots__ = ("name", "value", "default", "dtype", "doc")

    def __init__(self, name: str, default: Any, doc: str):
        self.name = name
        self.default = default
        self.dtype = type(default)
        self.doc = doc
        self.value = self._from_env(default)

    def _from_env(self, default: Any) -> Any:
        env = os.environ.get("FLAGS_" + self.name)
        if env is None:
            return default
        return _coerce(env, self.dtype)


def _coerce(value: Any, dtype: type) -> Any:
    if dtype is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    return dtype(value)


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, default: Any, doc: str = "") -> None:
    """Define a global flag (analog of PHI_DEFINE_EXPORTED_* macros)."""
    with _lock:
        if name in _REGISTRY:
            raise ValueError(f"flag '{name}' already defined")
        _REGISTRY[name] = _Flag(name, default, doc)


def flag_defined(name: str) -> bool:
    return name in _REGISTRY


def set_flags(flags: Dict[str, Any]) -> None:
    """Set flag values at runtime (analog of paddle.set_flags).

    Accepts both bare names and ``FLAGS_``-prefixed names.
    """
    with _lock:
        for key, value in flags.items():
            name = key[6:] if key.startswith("FLAGS_") else key
            flag = _REGISTRY.get(name)
            if flag is None:
                raise ValueError(f"unknown flag '{key}'")
            flag.value = _coerce(value, flag.dtype)


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    """Read flag values (analog of paddle.get_flags)."""
    if isinstance(flags, str):
        flags = [flags]
    out: Dict[str, Any] = {}
    for key in flags:
        name = key[6:] if key.startswith("FLAGS_") else key
        flag = _REGISTRY.get(name)
        if flag is None:
            raise ValueError(f"unknown flag '{key}'")
        out[key] = flag.value
    return out


def _get(name: str, default: Any = None) -> Any:
    flag = _REGISTRY.get(name)
    return flag.value if flag is not None else default


# ---------------------------------------------------------------------------
# Core framework flags (subset of the reference's 139, TPU-relevant ones).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Scan outputs of every eager op for NaN/Inf.")
define_flag("benchmark", False, "Block on each eager op for timing accuracy.")
define_flag("eager_op_jit_cache", True, "Cache per-op jitted executables keyed by op+attrs.")
define_flag("use_pallas_kernels", True, "Use Pallas TPU kernels for fused hot ops when available.")
define_flag("use_autotune", False, "Measured Pallas block-size selection with a persistent algorithm cache (one-time compile cost per new shape).")
define_flag("allocator_strategy", "xla", "Memory management owner: always XLA on TPU.")
define_flag("collective_timeout_s", 1800.0, "Watchdog timeout for in-flight collectives.")
define_flag("enable_async_trace", False, "Enable collective watchdog tracing.")
define_flag("tpu_matmul_precision", "default", "Default lax matmul precision (default|high|highest).")
