"""Error types and enforce helpers.

TPU-native analog of the reference's enforce layer
(reference: paddle/common/enforce.h, paddle/phi/core/enforce.h,
python surface ``paddle.base.core.EnforceNotMet`` and typed errors).

The reference's macros capture C++ stack traces; here Python tracebacks
serve that role, so the value we keep is the *typed error taxonomy* that
user code and tests match against.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "PermissionDeniedError",
    "ResourceExhaustedError",
    "PreconditionNotMetError",
    "UnimplementedError",
    "UnavailableError",
    "ExecutionTimeoutError",
    "FatalError",
    "enforce",
    "enforce_eq",
    "enforce_gt",
    "enforce_ge",
    "enforce_not_none",
]


class EnforceNotMet(RuntimeError):
    """Base error, analog of paddle's EnforceNotMet."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


class FatalError(EnforceNotMet):
    pass


def enforce(cond: bool, msg, err: type = PreconditionNotMetError) -> None:
    """Analog of PADDLE_ENFORCE(cond, msg).

    ``msg`` may be a zero-arg callable for messages that are costly to
    build (evaluated only on failure).
    """
    if not cond:
        raise err(msg() if callable(msg) else msg)


def enforce_eq(a, b, msg: str = "", err: type = InvalidArgumentError) -> None:
    if a != b:
        raise err(f"expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg: str = "", err: type = InvalidArgumentError) -> None:
    if not a > b:
        raise err(f"expected {a!r} > {b!r}. {msg}")


def enforce_ge(a, b, msg: str = "", err: type = InvalidArgumentError) -> None:
    if not a >= b:
        raise err(f"expected {a!r} >= {b!r}. {msg}")


def enforce_not_none(value, name: str = "value"):
    if value is None:
        raise NotFoundError(f"{name} should not be None")
    return value
