"""Global RNG state built on JAX functional keys.

Analog of the reference's global generator (paddle.seed → phi generators)
plus the hybrid-parallel RNG state tracker
(reference: python/paddle/distributed/fleet/layers/mpu/random.py:34,99
``RNGStatesTracker`` — named RNG states so TP ranks drop out identically
where required and differently where required).

The state holds a jax PRNG key. Random ops split the key per call. When a
traced seed tensor is pushed (``fork_traced``), all keys derive from a
traced value, so randomness threads correctly through jitted train steps
instead of baking into the compiled graph.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict

import jax

__all__ = ["seed", "get_key", "get_rng_state", "set_rng_state",
           "RNGStatesTracker", "get_rng_tracker", "fork_traced"]

_state = threading.local()


def _key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.key(0)
    return _state.key


def seed(s: int) -> None:
    """Set the global seed (paddle.seed)."""
    _state.key = jax.random.key(s)


def get_key():
    """Split one subkey off the global state."""
    k = _key()
    _state.key, sub = jax.random.split(k)
    return sub


def get_rng_state():
    return _key()


def set_rng_state(key) -> None:
    _state.key = key


@contextlib.contextmanager
def fork_traced(seed_tensor):
    """Temporarily derive all randomness from a traced seed (for jitted steps).

    The tracker's dropout-site counter restarts at 0 for the traced
    region: site numbering must be a pure function of the PROGRAM (site
    0..K in trace order), not of how many traces ran before in this
    process — otherwise retracing the same step (or compiling a fresh
    engine after restore_checkpoint) would bake different fold
    constants and silently change every dropout mask. Exact resume
    (tests/test_fault_tolerance.py) pins this."""
    from ..tensor import Tensor

    if isinstance(seed_tensor, Tensor):
        seed_tensor = seed_tensor._value
    seed_val = seed_tensor.reshape(()).astype("uint32")
    prev = _key()
    prev_traced = getattr(_state, "traced_seed", None)
    _state.key = jax.random.key(seed_val)
    _state.traced_seed = seed_val
    tracker = get_rng_tracker()
    prev_counter = tracker._entry_counter
    tracker._entry_counter = 0
    try:
        yield
    finally:
        _state.key = prev
        _state.traced_seed = prev_traced
        tracker._entry_counter = prev_counter


def traced_seed():
    """The traced per-step seed, when inside fork_traced (else None).
    RNG state trackers fold this in so dropout masks differ per step
    inside a compiled train step instead of baking into the graph."""
    return getattr(_state, "traced_seed", None)


LOCAL_SEED = "local_seed"
GLOBAL_SEED = "global_seed"


class RNGStatesTracker:
    """Named RNG states for hybrid-parallel dropout
    (reference: fleet/layers/mpu/random.py:34 RNGStatesTracker — CUDA RNG
    states so dropout inside TP regions differs per mp rank
    ('local_seed') while dropout outside is identical across mp ranks
    ('global_seed')).

    TPU-native: states are jax PRNG keys. Inside a compiled step
    (fork_traced active) keys fold in the traced per-step seed — so masks
    vary per step without retracing — plus a per-entry counter so
    distinct dropout sites draw distinct streams; 'local_seed'
    additionally folds the mp axis_index so each mp rank gets an
    independent stream for mp-sharded tensors.
    """

    def __init__(self):
        self.states_: Dict[str, object] = {}
        self.seeds_ = set()
        self._entry_counter = 0

    def add(self, name: str, s: int) -> None:
        if s in self.seeds_:
            raise ValueError(f"seed {s} already exists")
        if name in self.states_:
            raise ValueError(f"rng state '{name}' already exists")
        self.seeds_.add(s)
        self.states_[name] = jax.random.key(s)

    def reset(self) -> None:
        self.states_ = {}
        self.seeds_ = set()
        self._entry_counter = 0

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name: str = GLOBAL_SEED):
        if name not in self.states_:
            raise ValueError(f"rng state '{name}' not added")
        key = self.states_[name]
        folded = False
        ts = traced_seed()
        if ts is not None:  # inside a compiled step: vary per step & site
            key = jax.random.fold_in(key, ts)
            key = jax.random.fold_in(key, self._entry_counter)
            self._entry_counter += 1
            folded = True
        if name == LOCAL_SEED:
            from ..distributed import collective as _C

            if _C.in_spmd_region():
                from jax import lax

                try:
                    key = jax.random.fold_in(key, lax.axis_index("mp"))
                    folded = True
                except NameError:
                    pass
        prev = _key()
        _state.key = key
        try:
            yield
        finally:
            if not folded:
                # eager: persist the advanced key; traced: deliberately
                # discard (a tracer must never escape into host state)
                self.states_[name] = _state.key
            _state.key = prev


_tracker = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _tracker
