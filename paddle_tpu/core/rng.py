"""Global RNG state built on JAX functional keys.

Analog of the reference's global generator (paddle.seed → phi generators)
plus the hybrid-parallel RNG state tracker
(reference: python/paddle/distributed/fleet/layers/mpu/random.py:34,99
``RNGStatesTracker`` — named RNG states so TP ranks drop out identically
where required and differently where required).

The state holds a jax PRNG key. Random ops split the key per call. When a
traced seed tensor is pushed (``fork_traced``), all keys derive from a
traced value, so randomness threads correctly through jitted train steps
instead of baking into the compiled graph.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict

import jax

__all__ = ["seed", "get_key", "get_rng_state", "set_rng_state",
           "RNGStatesTracker", "get_rng_tracker", "fork_traced"]

_state = threading.local()


def _key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.key(0)
    return _state.key


def seed(s: int) -> None:
    """Set the global seed (paddle.seed)."""
    _state.key = jax.random.key(s)


def get_key():
    """Split one subkey off the global state."""
    k = _key()
    _state.key, sub = jax.random.split(k)
    return sub


def get_rng_state():
    return _key()


def set_rng_state(key) -> None:
    _state.key = key


@contextlib.contextmanager
def fork_traced(seed_tensor):
    """Temporarily derive all randomness from a traced seed (for jitted steps)."""
    from ..tensor import Tensor

    if isinstance(seed_tensor, Tensor):
        seed_tensor = seed_tensor._value
    prev = _key()
    _state.key = jax.random.key(seed_tensor.reshape(()).astype("uint32"))
    try:
        yield
    finally:
        _state.key = prev


class RNGStatesTracker:
    """Named RNG states (mpu/random.py analog) for TP-consistent dropout."""

    def __init__(self):
        self.states_: Dict[str, object] = {}

    def add(self, name: str, s: int) -> None:
        if name in self.states_:
            raise ValueError(f"rng state '{name}' already exists")
        self.states_[name] = jax.random.key(s)

    def reset(self) -> None:
        self.states_ = {}

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name not in self.states_:
            raise ValueError(f"rng state '{name}' not added")
        prev = _key()
        _state.key = self.states_[name]
        try:
            yield
        finally:
            self.states_[name] = _state.key
            _state.key = prev


_tracker = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _tracker
