"""Data types and dtype utilities.

Analog of the reference's phi::DataType (paddle/phi/common/data_type.h)
and python-side ``paddle.float32`` etc. We standardise on numpy/jnp dtype
objects as the canonical representation — idiomatic for JAX — while
accepting the reference's string names everywhere.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes  # ships with jax

__all__ = [
    "float16", "bfloat16", "float32", "float64",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool_", "complex64", "complex128", "float8_e4m3fn", "float8_e5m2",
    "convert_dtype", "get_default_dtype", "set_default_dtype",
    "is_floating_dtype", "is_integer_dtype", "finfo", "iinfo",
]

float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64)
uint8 = jnp.dtype(jnp.uint8)
uint16 = jnp.dtype(jnp.uint16)
uint32 = jnp.dtype(jnp.uint32)
uint64 = jnp.dtype(jnp.uint64)
bool_ = jnp.dtype(jnp.bool_)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128)
float8_e4m3fn = jnp.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = jnp.dtype(ml_dtypes.float8_e5m2)

_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn, "float8_e5m2": float8_e5m2,
}

_default_dtype = float32


def convert_dtype(dtype) -> np.dtype:
    """Canonicalise a dtype spec (string / np / jnp / paddle-style name)."""
    if dtype is None:
        return _default_dtype
    if isinstance(dtype, str):
        name = dtype.split(".")[-1].lower()  # accept "paddle.float32"
        if name not in _ALIASES:
            raise ValueError(f"unsupported dtype string: {dtype!r}")
        return _ALIASES[name]
    return jnp.dtype(dtype)


def get_default_dtype() -> np.dtype:
    return _default_dtype


def set_default_dtype(dtype) -> None:
    global _default_dtype
    dtype = convert_dtype(dtype)
    if dtype not in (float16, bfloat16, float32, float64):
        raise TypeError(f"default dtype must be floating, got {dtype}")
    _default_dtype = dtype


def is_floating_dtype(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.floating)


def is_integer_dtype(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype), jnp.integer)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))
