"""The shared compile-lattice bucketing helper.

Dynamic sizes that reach a compiled program's shapes (serving sequence
lengths, KV page-pool sizes, MoE expert capacity) are quantized onto a
geometric lattice so jitter in the raw value never mints a new XLA
program: each distinct bucket is one compilation, and the bucket count
stays logarithmic in the dynamic range. One definition lives here —
``inference`` (sequence/page lattice), the chunked-prefill chunk size
(``ServingEngine(prefill_chunk=...)`` buckets with ``lo=page_size``,
making the chunk a power-of-two multiple of the page so chunk
frontiers land on page boundaries), and the MoE capacity path
(incubate/.../moe/moe_layer.py) must stay on the SAME discipline so
their compile-stability tests mean the same thing.
"""
from __future__ import annotations

__all__ = ["bucket"]


def bucket(n: int, lo: int = 64) -> int:
    """Smallest power-of-two multiple of ``lo`` that is >= ``n``."""
    b = lo
    while b < n:
        b *= 2
    return b
