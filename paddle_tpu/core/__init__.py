"""Core substrate: flags, errors, dtypes, op registry, dispatch, RNG.

Analog of the reference's L0/L1 layers (paddle/common + phi core); see
SURVEY.md §1. TPU-first: kernels are JAX functions, executables are cached
XLA programs, memory/streams belong to XLA/PJRT.
"""
from . import dispatch, dtype, enforce, flags, registry, rng  # noqa: F401
