"""Loader for the native C++ runtime library.

(The reference's native runtime spans allocator/executor/collective C++;
here the host-side pieces that XLA does NOT own — bootstrap KV store,
shared-memory dataloader transport — are C++ in csrc/, built into
paddle_tpu/lib/libpaddle_tpu_native.so and bound via ctypes since
pybind11 isn't in this image.)

The library is built on demand with g++ if the .so is missing (first
import on a fresh checkout); callers treat ``load() is None`` as
"native unavailable" and fall back to pure-Python paths.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SO = os.path.join(_ROOT, "lib", "libpaddle_tpu_native.so")
_CSRC = os.path.join(os.path.dirname(_ROOT), "csrc")


def _build() -> bool:
    if not os.path.isdir(_CSRC):
        return False
    try:
        subprocess.run(["make", "-s"], cwd=_CSRC, check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO)
    except Exception:
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.tcpstore_server_start.restype = c.c_void_p
    lib.tcpstore_server_start.argtypes = [c.c_int, c.POINTER(c.c_int)]
    lib.tcpstore_server_stop.argtypes = [c.c_void_p]
    lib.tcpstore_connect.restype = c.c_int
    lib.tcpstore_connect.argtypes = [c.c_char_p, c.c_int]
    lib.tcpstore_close.argtypes = [c.c_int]
    lib.tcpstore_set.restype = c.c_int
    lib.tcpstore_set.argtypes = [c.c_int, c.c_char_p,
                                 c.POINTER(c.c_uint8), c.c_uint32]
    lib.tcpstore_get.restype = c.c_int64
    lib.tcpstore_get.argtypes = [c.c_int, c.c_char_p, c.c_int64,
                                 c.POINTER(c.POINTER(c.c_uint8))]
    lib.tcpstore_free.argtypes = [c.POINTER(c.c_uint8)]
    lib.tcpstore_add.restype = c.c_int64
    lib.tcpstore_add.argtypes = [c.c_int, c.c_char_p, c.c_int64]
    lib.tcpstore_wait.restype = c.c_int
    lib.tcpstore_wait.argtypes = [c.c_int, c.c_char_p, c.c_int64]
    lib.tcpstore_check.restype = c.c_int
    lib.tcpstore_check.argtypes = [c.c_int, c.c_char_p]
    lib.tcpstore_delete.restype = c.c_int
    lib.tcpstore_delete.argtypes = [c.c_int, c.c_char_p]

    lib.shmring_create.restype = c.c_void_p
    lib.shmring_create.argtypes = [c.c_char_p, c.c_uint64]
    lib.shmring_attach.restype = c.c_void_p
    lib.shmring_attach.argtypes = [c.c_char_p]
    lib.shmring_write.restype = c.c_int
    lib.shmring_write.argtypes = [c.c_void_p, c.POINTER(c.c_uint8),
                                  c.c_uint64, c.c_int64]
    lib.shmring_read.restype = c.c_int64
    lib.shmring_read.argtypes = [c.c_void_p,
                                 c.POINTER(c.POINTER(c.c_uint8)),
                                 c.c_int64]
    lib.shmring_free.argtypes = [c.POINTER(c.c_uint8)]
    lib.shmring_close.argtypes = [c.c_void_p]
    lib.shmring_detach.argtypes = [c.c_void_p]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it first if needed; None if neither
    loading nor building is possible."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_SO) and not _build():
            return None
        try:
            _LIB = _bind(ctypes.CDLL(_SO))
        except OSError:
            _LIB = None
        return _LIB
