"""Op registry and eager executor.

TPU-native re-design of the reference's PHI kernel registry/dispatch
(reference: paddle/phi/core/kernel_factory.h:58,240,316 — KernelKey/
Kernel/KernelFactory::SelectKernelOrThrowError; registration macro
paddle/phi/core/kernel_registry.h:196 PD_REGISTER_KERNEL).

Where the reference maps (op name, backend, dtype, layout) -> a C++ kernel
that launches CUDA, here every op is a *pure JAX function* and "kernel
selection" becomes: pick the op's jax/Pallas implementation and fetch (or
build) a cached XLA executable keyed by (op, static attrs) — jax.jit then
keys on shapes/dtypes, mirroring KernelKey. This addresses the reference's
per-op dispatch on a compiled device: each eager op call is one cached
PJRT executable launch, and under a whole-graph trace (to_static) the same
op functions inline into a single XLA program with no per-op overhead.

Attrs convention: tensor inputs are positional-or-keyword args holding
arrays; anything non-array (ints, floats passed as attrs, bools, strings,
tuples, None) is treated as a *static attribute* baked into the cache key,
exactly like the reference's op attributes on an OpDesc.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from . import flags
from .enforce import AlreadyExistsError, NotFoundError

__all__ = ["OpDef", "register_op", "register_grad", "get_op", "OpCall", "run_op"]

Tracer = jax.core.Tracer


class OpDef:
    """A registered operator: forward jax fn + optional explicit grad fn."""

    __slots__ = ("name", "fn", "grad_fn", "differentiable")

    def __init__(self, name: str, fn: Callable, differentiable: bool = True):
        self.name = name
        self.fn = fn
        self.grad_fn: Optional[Callable] = None
        self.differentiable = differentiable

    def __repr__(self):
        return f"OpDef({self.name})"


_REGISTRY: Dict[str, OpDef] = {}
_lock = threading.Lock()


def register_op(name: str, fn: Callable, differentiable: bool = True) -> OpDef:
    """Register a forward kernel (analog of PD_REGISTER_KERNEL)."""
    with _lock:
        if name in _REGISTRY:
            raise AlreadyExistsError(f"op '{name}' already registered")
        opdef = OpDef(name, fn, differentiable)
        _REGISTRY[name] = opdef
        return opdef


def register_grad(name: str, grad_fn: Callable) -> None:
    """Attach an explicit grad kernel to an op.

    Signature: grad_fn(in_values, out_values, out_grads, **attrs)
      -> tuple of grads aligned with the op's tensor inputs (None allowed).
    Ops without an explicit grad use the generic jax.vjp path.
    """
    get_op(name).grad_fn = grad_fn


def get_op(name: str) -> OpDef:
    opdef = _REGISTRY.get(name)
    if opdef is None:
        raise NotFoundError(f"op '{name}' not registered")
    return opdef


def is_tensor_like(x: Any) -> bool:
    return isinstance(x, (jax.Array, Tracer, np.ndarray, np.generic))


def _canon_static(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(_canon_static(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _canon_static(x)) for k, x in v.items()))
    return v


def _type_sig(v: Any) -> Any:
    """Type signature of a static arg, part of the executable-cache key:
    the cache is an ``==``-keyed lru_cache and ``1 == 1.0 == True`` hash
    alike in Python, but the closed-over scalar's TYPE changes jnp
    promotion (x + 1 is int32, x + 1.0 float32) — so same-valued,
    differently-typed statics must not share an executable."""
    if isinstance(v, (list, tuple)):
        return ("seq",) + tuple(_type_sig(x) for x in v)
    if isinstance(v, dict):
        return ("map",) + tuple(sorted((k, _type_sig(x))
                                       for k, x in v.items()))
    return type(v).__name__


class OpCall:
    """A fully-bound op invocation: tensor slots split from static attrs.

    ``key`` uniquely identifies the flat callable, so jitted executables and
    vjp executables can be cached across calls (the reference's KernelFactory
    cache role).
    """

    __slots__ = ("opdef", "key", "flat_fn", "in_values")

    def __init__(self, opdef: OpDef, args: Sequence[Any], kwargs: Dict[str, Any]):
        self.opdef = opdef
        spec = []          # per positional slot: "T" or ("S", value)
        in_values = []
        for a in args:
            if is_tensor_like(a):
                spec.append("T")
                in_values.append(a)
            else:
                spec.append(("S", _canon_static(a), _type_sig(a)))
        kw_spec = []
        for k in sorted(kwargs):
            v = kwargs[k]
            if is_tensor_like(v):
                kw_spec.append((k, "T"))
                in_values.append(v)
            else:
                kw_spec.append((k, ("S", _canon_static(v), _type_sig(v))))
        self.key = (opdef.name, tuple(spec), tuple(kw_spec))
        self.flat_fn = _flat_fn_cache(self.key, opdef.fn)
        self.in_values = in_values


@functools.lru_cache(maxsize=16384)
def _flat_fn_cache(key: Tuple, fn: Callable) -> Callable:
    """Build fn(*tensor_values) reconstructing the original call."""
    _, spec, kw_spec = key

    def flat_fn(*tvals):
        it = iter(tvals)
        args = [next(it) if s == "T" else s[1] for s in spec]
        kwargs = {k: (next(it) if s == "T" else s[1]) for k, s in kw_spec}
        return fn(*args, **kwargs)

    return flat_fn


@functools.lru_cache(maxsize=16384)
def _jitted(key: Tuple, flat_fn: Callable) -> Callable:
    return jax.jit(flat_fn)


@functools.lru_cache(maxsize=16384)
def _jitted_vjp(key: Tuple, flat_fn: Callable) -> Callable:
    """Generic grad executable: (in_values, out_grads) -> input grads."""

    def vjp_flat(in_values, out_grads):
        _, vjp_fn = jax.vjp(lambda *a: flat_fn(*a), *in_values)
        return vjp_fn(out_grads)

    return jax.jit(vjp_flat)


def _check_finite(name: str, outs) -> None:
    for o in jax.tree_util.tree_leaves(outs):
        if jnp.issubdtype(o.dtype, jnp.floating) and not bool(jnp.all(jnp.isfinite(o))):
            raise FloatingPointError(f"NaN/Inf detected in output of op '{name}'")


def run_op(call: OpCall):
    """Execute the forward kernel, using the executable cache when eager.

    Under an outer trace (values are Tracers) the raw function is called so
    the op inlines into the enclosing XLA program.
    """
    tracing = any(isinstance(v, Tracer) for v in call.in_values)
    if tracing or not flags._get("eager_op_jit_cache", True):
        outs = call.flat_fn(*call.in_values)
    else:
        outs = _jitted(call.key, call.flat_fn)(*call.in_values)
        if flags._get("check_nan_inf", False):
            _check_finite(call.opdef.name, outs)
    return outs


def run_grad(call: OpCall, in_values, out_values, out_grads):
    """Execute the backward kernel for a recorded forward call.

    Uses the op's explicit grad kernel when registered, otherwise the
    generic jax.vjp path (jit-cached; XLA CSEs the recomputed forward with
    the original under whole-graph traces).
    """
    opdef = call.opdef
    if opdef.grad_fn is not None:
        _, spec, kw_spec = call.key
        attrs = {k: s[1] for k, s in kw_spec if s != "T"}
        grads = opdef.grad_fn(in_values, out_values, out_grads, **attrs)
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        return tuple(grads)
    tracing = any(isinstance(v, Tracer) for v in in_values) or any(
        isinstance(v, Tracer) for v in jax.tree_util.tree_leaves(out_grads)
    )
    if tracing or not flags._get("eager_op_jit_cache", True):
        _, vjp_fn = jax.vjp(lambda *a: call.flat_fn(*a), *in_values)
        grads = vjp_fn(out_grads)
    else:
        grads = _jitted_vjp(call.key, call.flat_fn)(tuple(in_values), out_grads)
    # jax returns float0 cotangents for non-differentiable (int) inputs.
    return tuple(
        None if (g is None or g.dtype == jax.dtypes.float0) else g for g in grads
    )
