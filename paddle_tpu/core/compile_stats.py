"""Compile-cache telemetry shared by the serving path (Predictor /
ServingEngine) and the training engine (distributed.engine
ParallelEngine) — a new signature at a compiled-program launch site is
an XLA compile, a repeated one is a cache hit, so after warmup a
healthy path shows ``compiles`` flat and ``cache_hits`` growing."""
from __future__ import annotations

from typing import Any, Dict

__all__ = ["CompileStats"]


class CompileStats:
    """Compile-cache telemetry for compiled-program launch sites.

    Every launch site notes its FULL shape signature (including
    lattice dims like the paged-pool size P — the shape jax.jit
    actually keys on, even when the host-side fn cache key doesn't)."""

    def __init__(self):
        self.compiles = 0
        self.cache_hits = 0
        self.tokens = 0
        self.bucket_tokens: Dict[Any, int] = {}
        self._seen = set()

    def note(self, kind: str, sig) -> bool:
        """Record one compiled-program launch; True if it compiles."""
        key = (kind, sig)
        if key in self._seen:
            self.cache_hits += 1
            return False
        self._seen.add(key)
        self.compiles += 1
        return True

    def count_tokens(self, bucket, n: int):
        self.tokens += int(n)
        self.bucket_tokens[bucket] = self.bucket_tokens.get(bucket, 0) \
            + int(n)

    def tokens_per_sec(self, elapsed_s: float) -> float:
        return self.tokens / elapsed_s if elapsed_s > 0 else 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {"compiles": self.compiles, "cache_hits": self.cache_hits,
                "tokens": self.tokens,
                "bucket_tokens": {str(k): v
                                  for k, v in self.bucket_tokens.items()}}

    def __repr__(self):
        return (f"CompileStats(compiles={self.compiles}, "
                f"cache_hits={self.cache_hits}, tokens={self.tokens})")
