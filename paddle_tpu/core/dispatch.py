"""Tensor-level op dispatch: the analog of the reference's generated
``<op>_ad_func`` eager functions.

(reference: paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:251
— each generated ad_func unwraps tensors, selects+runs the PHI kernel, then
constructs the GradNode. Here one generic ``apply`` plays that role for all
ops; the per-op public functions are built by the ``def_op`` decorator, and
AMP auto-cast hooks in at this chokepoint like eager_gen.py:515 does.)
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax.numpy as jnp

from .registry import OpCall, OpDef, is_tensor_like, register_grad, register_op, run_op
from ..autograd import engine

__all__ = ["apply", "def_op", "def_grad"]

# Set by paddle_tpu.amp to intercept op inputs for auto-cast; takes
# (op_name, tensor_values) -> tensor_values.
_amp_hook = None

# Set by paddle_tpu.profiler while recording: wraps each op dispatch in a
# host RecordEvent (the reference hooks its host tracer into the
# generated ad_funcs the same way).
_profile_hook = None

# Set by paddle_tpu.amp.debugging: observes (op_name, arg_values) at each
# dispatch (operator-stats collection, amp accuracy tooling).
_op_observer = None

# Set by static.nn.cond while discovering a branch closure's
# differentiable inputs: receives every non-stop_gradient Tensor an op
# consumes. During the capture run the branch executes under no_grad, so
# branch-internal intermediates are stop_gradient and only the EXTERNAL
# captured tensors (the closure boundary) reach the hook.
_input_observer = None

# Flipped to True by paddle_tpu.static on the first Variable creation;
# gates the static-recording scan off the eager hot path.
_static_used = [False]


def apply(opdef: OpDef, args, kwargs):
    from ..tensor import Tensor

    # static-graph recording: an op touching a symbolic Variable appends
    # an OpNode to its Program instead of executing (reference: static
    # mode's OpDesc append in base/framework.py; same chokepoint here).
    # _static_used stays False until the first static.data call, so
    # eager-only programs never pay the per-arg scan.
    if _static_used[0] and (
            any(getattr(a, "_is_static_var", False) for a in args)
            or any(getattr(v, "_is_static_var", False)
                   for v in kwargs.values())):
        from ..static import record_op

        return record_op(opdef, args, kwargs)

    conv_args = []
    in_tensors = []  # aligned with OpCall.in_values order (positional, then sorted kwargs)
    kw_tensors = []
    for a in args:
        if isinstance(a, Tensor):
            in_tensors.append(a)
            conv_args.append(a._value)
        else:
            if is_tensor_like(a):
                in_tensors.append(None)
            conv_args.append(a)
    conv_kwargs = {}
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, Tensor):
            kw_tensors.append(v)
            conv_kwargs[k] = v._value
        else:
            if is_tensor_like(v):
                kw_tensors.append(None)
            conv_kwargs[k] = v
    in_tensors.extend(kw_tensors)

    if _amp_hook is not None:
        conv_args, conv_kwargs = _amp_hook(opdef.name, conv_args, conv_kwargs)

    call = OpCall(opdef, conv_args, conv_kwargs)
    requires_grad = opdef.differentiable and engine.is_grad_enabled() and any(
        t is not None and not t.stop_gradient for t in in_tensors
    )
    if _op_observer is not None:
        _op_observer(opdef.name, conv_args)
    if _input_observer is not None:
        for t in in_tensors:
            if t is not None and not t.stop_gradient:
                _input_observer(t)
    if _profile_hook is not None:
        with _profile_hook(opdef.name):
            outs = run_op(call)
    else:
        outs = run_op(call)

    multi = isinstance(outs, tuple)
    out_list = list(outs) if multi else [outs]
    out_tensors = [Tensor(o, stop_gradient=not requires_grad) for o in out_list]
    if requires_grad:
        engine.record_op(call, in_tensors, out_tensors, outs)
    # eager SPMD metadata propagation (reference: per-op InferSpmd) —
    # only runs when some input carries a dist_attr annotation
    if any(t is not None and getattr(t, "dist_attr", None) is not None
           for t in in_tensors):
        from ..distributed.auto_parallel import spmd_rules

        spmd_rules.infer(opdef.name, in_tensors, out_tensors, args, kwargs)
    return tuple(out_tensors) if multi else out_tensors[0]


def def_op(name: str, differentiable: bool = True) -> Callable:
    """Register a jax kernel and return the public Tensor-level function."""

    def deco(fn):
        opdef = register_op(name, fn, differentiable)

        @functools.wraps(fn)
        def public(*args, **kwargs):
            return apply(opdef, args, kwargs)

        public.opdef = opdef
        public.raw = fn
        return public

    return deco


def def_grad(name: str) -> Callable:
    """Register an explicit grad kernel for op ``name``."""

    def deco(fn):
        register_grad(name, fn)
        return fn

    return deco
