"""Flagship model families (GPT for hybrid-parallel training; the
reference trains these through Fleet — SURVEY.md §3.3)."""
from .gpt import (GPTConfig, GPTForCausalLM, GPTForCausalLMPipe, GPTModel,
                  GPTPretrainingCriterion, ernie_moe_base, gpt_125m,
                  gpt_13b, gpt_1p3b, gpt_350m, gpt_moe_tiny, gpt_tiny)
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaPretrainingCriterion, llama_13b, llama_7b,
                    llama_tiny, llama_tiny_draft)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "GPTForCausalLMPipe",
           "GPTPretrainingCriterion", "gpt_tiny", "gpt_125m", "gpt_350m",
           "gpt_1p3b", "gpt_13b", "gpt_moe_tiny", "ernie_moe_base",
           "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPretrainingCriterion", "llama_tiny", "llama_tiny_draft",
           "llama_7b", "llama_13b"]
