"""Flagship model families (GPT for hybrid-parallel training; the
reference trains these through Fleet — SURVEY.md §3.3)."""
from .gpt import (GPTConfig, GPTForCausalLM, GPTForCausalLMPipe, GPTModel,
                  GPTPretrainingCriterion, ernie_moe_base, gpt_125m,
                  gpt_13b, gpt_1p3b, gpt_350m, gpt_moe_tiny, gpt_tiny)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "GPTForCausalLMPipe",
           "GPTPretrainingCriterion", "gpt_tiny", "gpt_125m", "gpt_350m",
           "gpt_1p3b", "gpt_13b", "gpt_moe_tiny", "ernie_moe_base"]
