"""Llama model family + compiled KV-cache generation.

TPU-native redesign of the reference's Llama/fused-decode stack
(reference: the inference fast path fluid/operators/fused/
fused_multi_transformer_op.cu.h — a 2,023-LoC CUDA decoder loop with
cache-KV attention — plus masked_multihead_attention_kernel.cu per
decode step; python surface incubate/nn/layer/fused_transformer.py:1025
FusedMultiTransformer).

Architecture: RMSNorm (Pallas on TPU), rotary embeddings, GQA
(num_kv_heads < num_heads), SwiGLU MLP — all projections are
Column/RowParallelLinear so the model tensor-parallelizes over 'mp'
exactly like GPT.

Generation redesign: instead of a hand-written CUDA decoder, the decode
step is ONE jitted XLA program with *static-shape* preallocated KV
caches (head-major [B, KV, max_len, D]) updated in place via donated buffers —
the XLA-idiomatic equivalent of the paged cache-KV loop. Prefill and
decode share a single forward path (offset + sequence masking), so the
program compiles twice (prefill shape, decode shape) and never again.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from .. import ops
from ..autograd import no_grad
from ..core.dispatch import def_op
from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.container import LayerList
from ..nn.norm import RMSNorm
from ..framework.param_attr import ParamAttr
from ..nn import initializer as I
from ..ops.attention import flash_attention
from ..distributed.fleet.layers.mpu import (ColumnParallelLinear,
                                            RowParallelLinear,
                                            VocabParallelEmbedding,
                                            parallel_cross_entropy)
from ..observability import annotate as _annotate
from ..tensor import Tensor

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPretrainingCriterion", "LlamaRMSNorm", "llama_tiny",
           "llama_7b", "llama_13b"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 0               # 0 -> num_heads (MHA)
    intermediate_size: int = 11008
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    dtype: str = "float32"

    def __post_init__(self):
        if not self.num_kv_heads:
            self.num_kv_heads = self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, L, V = self.hidden_size, self.num_layers, self.vocab_size
        kv = self.num_kv_heads * self.head_dim
        per_layer = (h * h + 2 * h * kv + h * h
                     + 3 * h * self.intermediate_size + 2 * h)
        head = 0 if self.tie_word_embeddings else V * h
        return V * h + L * per_layer + h + head


def _init_attr(std):
    return ParamAttr(initializer=I.Normal(mean=0.0, std=std))


def _rope_tables(cfg: LlamaConfig, dtype=jnp.float32):
    D = cfg.head_dim
    inv = 1.0 / cfg.rope_theta ** (np.arange(0, D, 2, dtype=np.float64) / D)
    t = np.arange(cfg.max_position_embeddings, dtype=np.float64)
    freqs = np.outer(t, inv)
    emb = np.concatenate([freqs, freqs], axis=-1)
    return (jnp.asarray(np.cos(emb), dtype), jnp.asarray(np.sin(emb), dtype))


from ..ops.nn_ops import rotate_half as _rot_half  # noqa: E402


def _apply_rope(x, cos, sin, offset):
    """x: [B, S, H, D] values; cos/sin: [max, D]; offset: traced or int,
    or a PER-ROW vector [B] (ragged batches: each row rotates at its own
    absolute positions)."""
    S = x.shape[1]
    off = jnp.asarray(offset, jnp.int32)
    if off.ndim:
        pos = off[:, None] + jnp.arange(S, dtype=jnp.int32)[None]  # [B,S]
        c = cos[pos][:, :, None, :]                          # [B,S,1,D]
        s = sin[pos][:, :, None, :]
    else:
        c = lax.dynamic_slice_in_dim(cos, offset, S, axis=0)[None, :,
                                                             None, :]
        s = lax.dynamic_slice_in_dim(sin, offset, S, axis=0)[None, :,
                                                             None, :]
    return x * c.astype(x.dtype) + _rot_half(x) * s.astype(x.dtype)


_kernel_warned: set = set()


def _dispatch_kernel(name, supported, kernel, fallback):
    """Pallas-kernel dispatch policy, shared by the cache/paged
    attention paths: try the kernel when the flag + shape gate + TPU
    backend allow, warn ONCE PER KERNEL on failure, fall back to XLA."""
    from ..core import flags as _flags

    # the semantic scope names BOTH outcomes (kernel or XLA fallback)
    # after the kernel, so device traces show e.g. `decode_attention`
    # over whichever lowering actually ran
    if (_flags._get("use_pallas_kernels", True) and supported()
            and (jax.default_backend() != "cpu")):
        try:
            with _annotate(name):
                return kernel()
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            if name not in _kernel_warned:
                _kernel_warned.add(name)
                import warnings

                warnings.warn(f"{name}: Pallas kernel unavailable "
                              f"({type(e).__name__}: {e}); using dense "
                              "XLA fallback")
    with _annotate(name):
        return fallback()


@def_op("llama_rms_norm")
def _rms_norm_dispatch(x, weight, epsilon=1e-5):
    from ..ops.pallas.rms_norm import (rms_norm_dense, rms_norm_fused,
                                       rms_norm_supported)

    return _dispatch_kernel(
        "rms_norm",
        lambda: rms_norm_supported(x.shape),
        lambda: rms_norm_fused(x, weight, float(epsilon)),
        lambda: rms_norm_dense(x, weight, float(epsilon)))


class LlamaRMSNorm(RMSNorm):
    """RMSNorm routed through the shared Pallas dispatch policy: the
    fused one-VMEM-pass kernel (ops/pallas/rms_norm.py) when the Mosaic
    shape gate admits the geometry on TPU, the numerically identical
    dense XLA path otherwise — the swap only changes the lowering,
    never the results (both accumulate in f32 with the same formula)."""

    def forward(self, x):
        return _rms_norm_dispatch(x, self.weight,
                                  epsilon=float(self._epsilon))


def _cache_attention(q, k_cache, v_cache, offset, S):
    """Attention of q [B,S,H,D] against static caches [B,KV,M,D]; valid
    kv positions are <= offset + row (the fused_multi_transformer
    cache-KV attention). On TPU this is the Pallas decode kernel —
    cache streamed in blocks, DMA stops at the valid frontier, GQA
    grouped natively (ops/pallas/decode_attention.py); the portable
    path is a full-cache matmul + length mask in XLA."""
    from ..ops.pallas import decode_attention as _da

    return _dispatch_kernel(
        "decode_attention",
        lambda: _da.supported(q.shape, k_cache.shape),
        lambda: _da.decode_attention(q, k_cache, v_cache, offset),
        lambda: _cache_attention_dense(q, k_cache, v_cache, offset, S))


def _paged_attention(q, k_pool, v_pool, tables, lengths, S):
    """Paged-cache attention dispatch: Pallas block-table kernel on TPU
    (reference capability: block_multi_head_attention_kernel.cu), XLA
    gather + ragged dense mask elsewhere."""
    from ..ops.pallas import decode_attention as _da

    return _dispatch_kernel(
        "paged_decode_attention",
        lambda: _da.paged_supported(q.shape, k_pool.shape),
        lambda: _da.paged_decode_attention(q, k_pool, v_pool, tables,
                                           lengths),
        lambda: _da.paged_attention_dense(q, k_pool, v_pool, tables,
                                          lengths))


def _unified_paged_attention(q, k_pool, v_pool, tables, starts, valid):
    """Unified mixed prefill-chunk/decode attention dispatch over the
    page pool (the Ragged Paged Attention design): Pallas ragged kernel
    on TPU, gathered doubly-ragged dense mask elsewhere."""
    from ..ops.pallas import ragged_paged_attention as _ra

    return _dispatch_kernel(
        "ragged_paged_attention",
        lambda: _ra.ragged_supported(q.shape, k_pool.shape),
        lambda: _ra.ragged_paged_attention(q, k_pool, v_pool, tables,
                                           starts, valid),
        lambda: _ra.ragged_paged_attention_dense(q, k_pool, v_pool,
                                                 tables, starts, valid))


def _cache_attention_dense(q, k_cache, v_cache, offset, S):
    """Caches are head-major [B, KV, M, D]; offset scalar or [B]. The
    math lives in ops/pallas/decode_attention._dense_ragged (shared
    with the paged fallback)."""
    from ..ops.pallas.decode_attention import _dense_ragged

    B = q.shape[0]
    off = jnp.broadcast_to(jnp.asarray(offset, jnp.int32).reshape(-1),
                           (B,))
    return _dense_ragged(q, k_cache, v_cache, off)


class LlamaAttention(Layer):
    """GQA attention with rotary embeddings; qkv column-, out row-parallel."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        std = config.initializer_range
        h, D = config.hidden_size, config.head_dim
        kv = config.num_kv_heads * D
        from ..core.enforce import enforce

        self.q_proj = ColumnParallelLinear(h, h, weight_attr=_init_attr(std),
                                           has_bias=False,
                                           gather_output=False)
        enforce(config.num_heads % self.q_proj.world_size == 0
                and config.num_kv_heads % self.q_proj.world_size == 0,
                f"num_heads {config.num_heads} and num_kv_heads "
                f"{config.num_kv_heads} must divide mp degree "
                f"{self.q_proj.world_size} (GQA TP sharding)")
        self.k_proj = ColumnParallelLinear(h, kv, weight_attr=_init_attr(std),
                                           has_bias=False,
                                           gather_output=False)
        self.v_proj = ColumnParallelLinear(h, kv, weight_attr=_init_attr(std),
                                           has_bias=False,
                                           gather_output=False)
        self.o_proj = RowParallelLinear(
            h, h, weight_attr=_init_attr(std / math.sqrt(2 * config.num_layers)),
            has_bias=False, input_is_parallel=True)
        # built eagerly: creating constants inside a jit trace and caching
        # them on the layer would leak tracers
        self._rope = _rope_tables(config, jnp.float32)

    def _tables(self, dtype):
        return self._rope

    def forward(self, x, cache=None, offset=0, valid=None):
        cfg = self.config
        B, S = x.shape[0], x.shape[1]
        D = cfg.head_dim
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        n_local = q.shape[-1] // D
        nkv_local = k.shape[-1] // D
        qv = q._value.reshape(B, S, n_local, D)
        kv_ = k._value.reshape(B, S, nkv_local, D)
        vv = v._value.reshape(B, S, nkv_local, D)
        cos, sin = self._tables(jnp.float32)
        qv = _apply_rope(qv, cos, sin, offset)
        kv_ = _apply_rope(kv_, cos, sin, offset)

        if cache is not None:
            if len(cache) == 3:         # paged: (k_pool, v_pool, tables)
                k_pool, v_pool, tables = cache
                page = k_pool.shape[2]
                off = jnp.broadcast_to(
                    jnp.asarray(offset, jnp.int32).reshape(-1), (B,))
                pos = off[:, None] + jnp.arange(S, dtype=jnp.int32)[None]
                if valid is not None:
                    # unified mixed prefill-chunk/decode step: only the
                    # first valid[b] slots of row b are real tokens.
                    # CONTRACT: the caller's table carries ONE EXTRA
                    # trailing column that always maps to the trash
                    # page (inference/serving.py builds it) — dead
                    # slots' kv writes are redirected there instead of
                    # clobbering the row's own future cache slots
                    nv = jnp.asarray(valid, jnp.int32).reshape(B)
                    alive = jnp.arange(S, dtype=jnp.int32)[None] \
                        < nv[:, None]
                    pos = jnp.where(alive, pos,
                                    (tables.shape[1] - 1) * page)
                pid = jnp.take_along_axis(tables, pos // page, axis=1)
                slot = pos % page        # [B,S]
                # advanced-index scatter: [B,S] page ids + slots land
                # the new [B,S,KV,D] kv rows in their physical pages
                # (rows a row does not own are mapped to the trash page
                # by the table, see inference paged allocator)
                k_pool = k_pool.at[pid, :, slot, :].set(
                    kv_.astype(k_pool.dtype))
                v_pool = v_pool.at[pid, :, slot, :].set(
                    vv.astype(v_pool.dtype))
                if valid is not None:
                    # the trailing trash column is a write-side device
                    # only: attention sees the canonical [B, npages]
                    # table, so the key space (and the compiled
                    # attention shape) matches the two-program path
                    ov = _unified_paged_attention(
                        qv, k_pool, v_pool, tables[:, :-1], off, nv)
                else:
                    ov = _paged_attention(qv, k_pool, v_pool, tables,
                                          off, S)
                out = Tensor(ov.reshape(B, S, n_local * D),
                             stop_gradient=True)
                return self.o_proj(out), (k_pool, v_pool, tables)
            from ..core.enforce import enforce

            enforce(valid is None, "valid (unified ragged metadata) is "
                    "only served over the paged KV cache")
            k_cache, v_cache = cache    # head-major [B, KV, M, D]
            off = jnp.asarray(offset, jnp.int32)
            k_new = jnp.swapaxes(kv_, 1, 2).astype(k_cache.dtype)
            v_new = jnp.swapaxes(vv, 1, 2).astype(v_cache.dtype)
            if off.ndim:                # ragged: per-row write positions
                dus = lambda c, u, o: lax.dynamic_update_slice_in_dim(
                    c, u, o, axis=1)    # [KV,M,D] <- [KV,S,D] @ row off
                k_cache = jax.vmap(dus)(k_cache, k_new, off)
                v_cache = jax.vmap(dus)(v_cache, v_new, off)
            else:
                k_cache = lax.dynamic_update_slice_in_dim(
                    k_cache, k_new, offset, axis=2)
                v_cache = lax.dynamic_update_slice_in_dim(
                    v_cache, v_new, offset, axis=2)
            ov = _cache_attention(qv, k_cache, v_cache, offset, S)
            out = Tensor(ov.reshape(B, S, n_local * D), stop_gradient=True)
            return self.o_proj(out), (k_cache, v_cache)

        # training path: tape-tracked rope + flash attention. GQA heads
        # pass through as-is — flash_attention groups q per kv head by
        # broadcast (no repeated K/V copies on the XLA path)
        q_r = _rope_op(q, B, S, n_local, D, cos, sin)
        k_r = _rope_op(k, B, S, nkv_local, D, cos, sin)
        v_r = ops.reshape(v, (B, S, nkv_local, D))
        o = flash_attention(q_r, k_r, v_r, causal=True)
        o = ops.reshape(o, (B, S, n_local * D))
        return self.o_proj(o)


def _rope_op(x, B, S, n, D, cos, sin):
    """Tape-differentiable rope on a [B,S,n*D] projection output."""
    x4 = ops.reshape(x, (B, S, n, D))
    from ..ops.nn_ops import fused_rope

    out, _ = fused_rope(x4, x4, cos[:S], sin[:S])
    return out


class LlamaMLP(Layer):
    """SwiGLU MLP: gate/up column-parallel, down row-parallel."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        std = config.initializer_range
        h, m = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(
            h, m, weight_attr=_init_attr(std), has_bias=False,
            gather_output=False)
        self.up_proj = ColumnParallelLinear(
            h, m, weight_attr=_init_attr(std), has_bias=False,
            gather_output=False)
        self.down_proj = RowParallelLinear(
            m, h, weight_attr=_init_attr(std / math.sqrt(2 * config.num_layers)),
            has_bias=False, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(config.hidden_size,
                                            epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = LlamaRMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cache=None, offset=0, valid=None):
        if cache is not None:
            with _annotate("attention"):
                a, new_cache = self.self_attn(self.input_layernorm(x),
                                              cache=cache, offset=offset,
                                              valid=valid)
            x = x + a
            with _annotate("mlp"):
                x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        with _annotate("attention"):
            x = x + self.self_attn(self.input_layernorm(x))
        with _annotate("mlp"):
            x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=_init_attr(config.initializer_range))
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_layers)])
        self.norm = LlamaRMSNorm(config.hidden_size,
                                 epsilon=config.rms_norm_eps)

    def forward(self, input_ids, caches=None, offset=0, valid=None):
        # named scopes per layer: XLA metadata (and thus the Perfetto /
        # TensorBoard device trace) reads `llama/layer3/attention`
        # instead of bare fusions
        with _annotate("llama"):
            with _annotate("embed"):
                x = self.embed_tokens(input_ids)
            if caches is not None:
                new_caches = []
                for i, (layer, cache) in enumerate(zip(self.layers,
                                                       caches)):
                    with _annotate(f"layer{i}"):
                        x, nc = layer(x, cache=cache, offset=offset,
                                      valid=valid)
                    new_caches.append(nc)
                return self.norm(x), new_caches
            for i, layer in enumerate(self.layers):
                with _annotate(f"layer{i}"):
                    x = layer(x)
            return self.norm(x)


class LlamaForCausalLM(Layer):
    """Llama with (untied by default) vocab-parallel LM head + compiled
    KV-cache generation (the fused_multi_transformer decode path)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size,
                weight_attr=_init_attr(config.initializer_range),
                has_bias=False, gather_output=False)
        if config.dtype not in ("float32", None):
            self.astype(config.dtype)
        self._decode_fns = {}

    def _logits(self, x):
        if self.config.tie_word_embeddings:
            from ..distributed.fleet.layers.mpu.mp_ops import (_c_identity,
                                                               mp_active)

            w = self.llama.embed_tokens.weight
            if mp_active():
                x = _c_identity(x)
            return ops.matmul(x, w, transpose_y=True)
        return self.lm_head(x)

    def forward(self, input_ids, caches=None, offset=0, valid=None):
        if caches is not None:
            x, new_caches = self.llama(input_ids, caches=caches,
                                       offset=offset, valid=valid)
            return self._logits(x), new_caches
        return self._logits(self.llama(input_ids))

    # -- generation (compiled decode loop) ------------------------------
    def _empty_caches(self, B: int, max_len: int, dtype):
        # head-major [B, KV, M, D]: each head's [M, D] plane contiguous
        # (Mosaic-tileable for the Pallas decode kernel)
        cfg = self.config
        shape = (B, cfg.num_kv_heads, max_len, cfg.head_dim)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(cfg.num_layers)]

    def _step_fn(self, B: int, S: int, max_len: int):
        """One jitted forward-with-cache step; compiled per (B, S)."""
        key = (B, S, max_len)
        if key in self._decode_fns:
            return self._decode_fns[key]
        params = list(self.parameters())
        from ..distributed.engine import bind_params

        def step(pvals, ids, caches, offset):
            with no_grad(), bind_params(params, pvals):
                logits, new_caches = self.forward(
                    Tensor(ids, stop_gradient=True), caches=caches,
                    offset=offset)
            return logits._value, new_caches

        self._decode_fns[key] = jax.jit(step, donate_argnums=(2,))
        return self._decode_fns[key]

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, max_length: Optional[int] = None):
        """Greedy (or temperature/top-k) generation with static caches.

        Returns a Tensor [B, S_prompt + max_new_tokens]. Exactly two XLA
        programs run: prefill [B, S_prompt] and decode [B, 1] — the
        decode program is reused every token with donated cache buffers.
        """
        ids = input_ids._value if isinstance(input_ids, Tensor) else \
            jnp.asarray(input_ids)
        B, S0 = ids.shape
        M = max_length or min(self.config.max_position_embeddings,
                              S0 + max_new_tokens)
        from ..core.enforce import enforce

        enforce(S0 + max_new_tokens <= M,
                f"prompt ({S0}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the cache length {M} "
                f"(max_position_embeddings="
                f"{self.config.max_position_embeddings}); writes past the "
                "cache would silently clamp")
        p_dtype = self.parameters()[0]._value.dtype
        caches = self._empty_caches(B, M, p_dtype)
        pvals = tuple(p._value for p in self.parameters())

        prefill = self._step_fn(B, S0, M)
        logits, caches = prefill(pvals, ids, caches, 0)
        key = jax.random.PRNGKey(seed)

        def pick(logits_last, key):
            if temperature and temperature > 0:
                lg = logits_last / temperature
                if top_k:
                    kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
                    lg = jnp.where(lg < kth, -1e30, lg)
                return jax.random.categorical(key, lg, axis=-1)
            return jnp.argmax(logits_last, axis=-1)

        toks = [ids]
        step = self._step_fn(B, 1, M)
        nxt = pick(logits[:, -1].astype(jnp.float32), key)
        pos = S0
        for i in range(max_new_tokens - 1):
            toks.append(nxt[:, None])
            logits, caches = step(pvals, nxt[:, None], caches, pos)
            key, sub = jax.random.split(key)
            nxt = pick(logits[:, -1].astype(jnp.float32), sub)
            pos += 1
        toks.append(nxt[:, None])
        return Tensor(jnp.concatenate(toks, axis=1), stop_gradient=True)


class LlamaPretrainingCriterion(Layer):
    """Vocab-parallel LM loss (same contract as GPTPretrainingCriterion)."""

    def __init__(self, config: Optional[LlamaConfig] = None, mp_group=None):
        super().__init__()
        self._mp_group = mp_group

    def forward(self, logits, labels, loss_mask=None):
        loss = parallel_cross_entropy(logits, labels, self._mp_group)
        loss = ops.squeeze(loss, axis=-1)
        if loss_mask is not None:
            from .gpt import _masked_mean_over_splits

            m = ops.cast(loss_mask, str(loss.dtype))
            return _masked_mean_over_splits(ops.sum(loss * m), ops.sum(m))
        return ops.mean(loss)


def llama_tiny(**kw) -> LlamaConfig:
    return LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=4, num_kv_heads=2, intermediate_size=128,
                       max_position_embeddings=128, **kw)


def llama_tiny_draft(**kw) -> LlamaConfig:
    """Draft-sized companion to ``llama_tiny`` for speculative
    decoding: same vocabulary and position range (the serving engine
    requires both), roughly a quarter of the compute — one layer,
    half the width."""
    kw.setdefault("vocab_size", 256)
    kw.setdefault("max_position_embeddings", 128)
    return LlamaConfig(hidden_size=32, num_layers=1, num_heads=2,
                       num_kv_heads=1, intermediate_size=64, **kw)


def llama_7b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_13b(**kw) -> LlamaConfig:
    kw.setdefault("hidden_size", 5120)
    kw.setdefault("num_layers", 40)
    kw.setdefault("num_heads", 40)
    kw.setdefault("intermediate_size", 13824)
    return LlamaConfig(**kw)
