"""GPT model family — the flagship hybrid-parallel training model.

The reference framework trains GPT-3-style models through Fleet
HybridParallel (SURVEY.md §3.3 north-star call stack; TP layers
reference python/paddle/distributed/fleet/layers/mpu/mp_layers.py, fused
transformer reference python/paddle/incubate/nn/layer/fused_transformer.py).
This module builds the same architecture TPU-first:

- attention/MLP projections are ColumnParallelLinear / RowParallelLinear
  (mp-sharded weights as global jax.Arrays),
- attention core is the flash_attention op (Pallas kernel on TPU),
- the LM head ties the vocab-parallel embedding and the loss is the
  vocab-parallel softmax cross-entropy, so the full-vocab logits tensor
  never materializes unsharded,
- decoder blocks are homogeneous, so the pipeline engine can stack their
  params along a leading 'pp' stage axis (see meta_parallel/pp_utils).

Configs mirror the GPT-3 ladder used by BASELINE.md (125M/350M/1.3B/13B).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from .. import ops
from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.container import LayerList
from ..nn.common import Dropout, Embedding
from ..nn.norm import LayerNorm
from ..framework.param_attr import ParamAttr
from ..nn import initializer as I
from ..ops.attention import flash_attention
from ..distributed.fleet.layers.mpu import (ColumnParallelLinear,
                                            RowParallelLinear,
                                            VocabParallelEmbedding,
                                            parallel_cross_entropy)
from ..observability import annotate as _annotate
from ..distributed.fleet.layers.mpu.mp_ops import (_c_identity, mp_active,
                                                   mp_axes)
from ..tensor import Tensor

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion", "gpt_tiny", "gpt_125m", "gpt_350m",
           "gpt_1p3b", "gpt_13b"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 0          # 0 -> 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-5
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True
    dtype: str = "float32"
    # MoE (ERNIE-MoE style): num_experts > 0 replaces the MLP of every
    # `moe_every`-th block with an expert-parallel MoELayer
    num_experts: int = 0
    moe_every: int = 2
    moe_gate: str = "gshard"
    moe_aux_coef: float = 0.01

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        """Parameter count (embeddings included once when tied)."""
        h, L, V = self.hidden_size, self.num_layers, self.vocab_size
        per_layer = 4 * h * h + 4 * h + 2 * h * self.intermediate_size \
            + self.intermediate_size + h + 4 * h
        return V * h + self.max_position_embeddings * h + L * per_layer + 2 * h


def _init_attr(std):
    return ParamAttr(initializer=I.Normal(mean=0.0, std=std))


def _sep_axes():
    """Active context-parallel ('sep') mesh axes, or None.

    The reference's SEP splits sequence segments across ranks with P2P
    helpers but no ring-attention kernel (SURVEY.md §2.4 — CP absent).
    Here sep ranks hold contiguous sequence blocks and attention runs the
    exact ring algorithm (ops/ring_attention.py)."""
    from ..distributed import collective as C
    from ..distributed import fleet as _fleet

    if not C.in_spmd_region():
        return None
    hcg = _fleet.get_hybrid_communicate_group()
    if hcg is None or hcg.get_sep_parallel_world_size() <= 1:
        return None
    return hcg.get_sep_parallel_group().axis_names


def _masked_mean_over_splits(num, den):
    """Globally-correct masked mean when the batch/sequence is split over
    dp/sharding/sep: per-rank valid-token counts differ, so divide the
    LOCAL numerator by the GLOBAL denominator and pre-scale by the rank
    count — the engine's equal-weight pmean then yields
    sum(num)/sum(den) with correct per-token gradients."""
    from jax import lax as _lax

    from ..distributed import collective as C
    from ..tensor import Tensor as _T

    mesh = C.get_world_mesh() if C.in_spmd_region() else None
    if mesh is not None:
        axes = tuple(a for a in ("dp", "sharding", "sep", "ep")
                     if a in mesh.axis_names and mesh.shape[a] > 1)
        if axes:
            R = 1
            for a in axes:
                R *= mesh.shape[a]
            from ..distributed import collective as _C

            den = _T(_C.t_psum(den._value, axes), stop_gradient=True)
            num = num * float(R)
    return num / ops.clip(den, min=1.0)


def _sep_shard(value, axis: int):
    """This sep rank's contiguous block of ``axis`` (+ global offset)."""
    import jax.numpy as jnp
    from jax import lax as _lax

    from ..distributed import collective as C

    axes = _sep_axes()
    if axes is None:
        return value, 0
    n = 1
    for a in axes:
        n *= C.axis_size(a)
    idx = C.axis_index(axes)
    loc = value.shape[axis] // n
    off = idx * loc
    return _lax.dynamic_slice_in_dim(value, off, loc, axis=axis), off


class GPTAttention(Layer):
    """Causal self-attention; qkv column-parallel, out row-parallel."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_heads
        self.head_dim = config.head_dim
        std = config.initializer_range
        self.qkv_proj = ColumnParallelLinear(
            config.hidden_size, 3 * config.hidden_size,
            weight_attr=_init_attr(std), gather_output=False)
        self.out_proj = RowParallelLinear(
            config.hidden_size, config.hidden_size,
            weight_attr=_init_attr(std / math.sqrt(2 * config.num_layers)),
            input_is_parallel=True)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x, cache=None):
        B, S = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)                      # [B, S, 3*H_local]
        n_local = qkv.shape[-1] // (3 * self.head_dim)
        qkv = ops.reshape(qkv, (B, S, n_local, 3 * self.head_dim))
        q, k, v = ops.split(qkv, 3, axis=-1)        # [B, S, n_local, D]
        if cache is not None:
            k = ops.concat([cache[0], k], axis=1)
            v = ops.concat([cache[1], v], axis=1)
            new_cache = (k, v)
            out = flash_attention(q, k, v, causal=S > 1)
        elif _sep_axes() is not None:
            # context parallelism: seq is sep-sharded; exact ring attention
            new_cache = None
            from ..core.enforce import enforce as _enf

            _enf(not (self.training and self.config.attention_dropout > 0),
                 "attention_dropout is not supported with context "
                 "parallelism (ring attention) yet; set it to 0")
            from ..ops.ring_attention import ring_flash_attention

            out = ring_flash_attention(q, k, v, axes=_sep_axes(),
                                       causal=True)
        else:
            new_cache = None
            p = self.config.attention_dropout if self.training else 0.0
            if p:
                # attention probs are mp-sharded ([B,S,n_local,S]) — draw
                # from the 'local_seed' stream so each mp rank masks its
                # head-shard independently (Megatron RNG rule)
                from ..distributed.fleet.layers.mpu.random import \
                    local_dropout_key

                out = flash_attention(q, k, v, causal=True, dropout=p,
                                      dropout_key=local_dropout_key())
            else:
                out = flash_attention(q, k, v, causal=True)
        out = ops.reshape(out, (B, S, n_local * self.head_dim))
        out = self.out_proj(out)
        out = self.dropout(out)
        return (out, new_cache) if cache is not None else out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        std = config.initializer_range
        self.fc1 = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size,
            weight_attr=_init_attr(std), gather_output=False)
        self.fc2 = RowParallelLinear(
            config.intermediate_size, config.hidden_size,
            weight_attr=_init_attr(std / math.sqrt(2 * config.num_layers)),
            input_is_parallel=True)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x):
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


class GPTMoEMLP(Layer):
    """Expert-parallel MoE feed-forward (ERNIE-MoE block: reference
    incubate/distributed/models/moe/moe_layer.py used inside ERNIE)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        from ..incubate.distributed.models.moe import MoELayer

        self.moe = MoELayer(config.hidden_size,
                            d_hidden=config.intermediate_size,
                            num_experts=config.num_experts,
                            gate=config.moe_gate)
        self.dropout = Dropout(config.hidden_dropout)

    @property
    def aux_loss(self):
        return self.moe.aux_loss

    def forward(self, x):
        return self.dropout(self.moe(x))


class GPTDecoderLayer(Layer):
    """Pre-LN decoder block; homogeneous across the stack (pipelineable)."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        use_moe = (config.num_experts > 0
                   and layer_idx % max(1, config.moe_every) == 0)
        self.mlp = GPTMoEMLP(config) if use_moe else GPTMLP(config)

    def forward(self, x, cache=None):
        if cache is not None:
            with _annotate("attention"):
                a, new_cache = self.attn(self.ln1(x), cache=cache)
            x = x + a
            with _annotate("mlp"):
                x = x + self.mlp(self.ln2(x))
            return x, new_cache
        with _annotate("attention"):
            x = x + self.attn(self.ln1(x))
        with _annotate("mlp"):
            x = x + self.mlp(self.ln2(x))
        return x


class GPTEmbeddings(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        std = config.initializer_range
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=_init_attr(std))
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=_init_attr(std))
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, input_ids, position_offset=0):
        S = input_ids.shape[1]
        # offset may be a traced scalar (sep rank * block), so add it
        # rather than baking it into arange bounds
        pos = ops.arange(0, S, dtype="int32")
        if not isinstance(position_offset, int) or position_offset:
            pos = pos + position_offset
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        return self.dropout(x)


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = LayerList([GPTDecoderLayer(config, layer_idx=i)
                                 for i in range(config.num_layers)])
        self.final_ln = LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)

    def forward(self, input_ids, caches=None, position_offset=0):
        if caches is None and _sep_axes() is not None:
            # context parallel: each sep rank embeds+computes only its
            # contiguous sequence block; ring attention stitches them
            ids_local, off = _sep_shard(input_ids._value, axis=1)
            input_ids = Tensor(ids_local, stop_gradient=True)
            position_offset = off
        # named scopes per layer: device traces read `gpt/layer3/mlp`
        # instead of bare fusions
        with _annotate("gpt"):
            with _annotate("embed"):
                x = self.embeddings(input_ids, position_offset)
            if caches is not None:
                new_caches = []
                for i, (layer, cache) in enumerate(zip(self.layers,
                                                       caches)):
                    with _annotate(f"layer{i}"):
                        x, nc = layer(x, cache=cache)
                    new_caches.append(nc)
                return self.final_ln(x), new_caches
            for i, layer in enumerate(self.layers):
                with _annotate(f"layer{i}"):
                    x = layer(x)
            return self.final_ln(x)


class GPTForCausalLM(Layer):
    """GPT with a (tied) vocab-parallel LM head.

    In mp mode the head produces LOCAL logits [B, S, V/mp]; pair it with
    GPTPretrainingCriterion (vocab-parallel cross-entropy) so full logits
    never materialize (the reference pairs ColumnParallelLinear lm_head
    with ParallelCrossEntropy the same way).
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size,
                weight_attr=_init_attr(config.initializer_range),
                has_bias=False, gather_output=False)
        if config.dtype not in ("float32", None):
            self.astype(config.dtype)

    def _logits(self, x):
        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight  # [V(/mp), H]
            if mp_active():
                # identity fwd / mp-psum bwd: each rank's head produces a
                # PARTIAL dL/dx (its vocab shard only); sum before the
                # grad re-enters the replicated decoder (Megatron rule).
                x = _c_identity(x)
            return ops.matmul(x, w, transpose_y=True)       # local logits
        return self.lm_head(x)

    def forward(self, input_ids, caches=None, position_offset=0):
        if caches is not None:
            x, new_caches = self.gpt(input_ids, caches=caches,
                                     position_offset=position_offset)
            return self._logits(x), new_caches
        return self._logits(self.gpt(input_ids))

    @property
    def aux_loss(self):
        """Sum of MoE load-balance losses of the last forward (scaled by
        config.moe_aux_coef); 0 for dense models."""
        total = None
        for layer in self.gpt.layers:
            a = getattr(layer.mlp, "aux_loss", None)
            if a is not None:
                total = a if total is None else total + a
        if total is None:
            from ..tensor import to_tensor

            return to_tensor(0.0)
        return total * self.config.moe_aux_coef


class GPTPretrainingCriterion(Layer):
    """Shift-by-one LM loss over (possibly mp-local) logits."""

    def __init__(self, config: Optional[GPTConfig] = None, mp_group=None):
        super().__init__()
        self._mp_group = mp_group

    def forward(self, logits, labels, loss_mask=None):
        if _sep_axes() is not None and labels.shape[1] != logits.shape[1]:
            # context parallel: logits are seq-local — take the matching
            # label (and mask) block; mean-of-local-means == global mean
            lv, _ = _sep_shard(labels._value, axis=1)
            labels = Tensor(lv, stop_gradient=True)
            if loss_mask is not None:
                mv, _ = _sep_shard(loss_mask._value, axis=1)
                loss_mask = Tensor(mv, stop_gradient=True)
        loss = parallel_cross_entropy(logits, labels, self._mp_group)
        loss = ops.squeeze(loss, axis=-1)
        if loss_mask is not None:
            m = ops.cast(loss_mask, str(loss.dtype))
            num = ops.sum(loss * m)
            den = ops.sum(m)
            return _masked_mean_over_splits(num, den)
        return ops.mean(loss)


def gpt_tiny(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=128, **kw)


def gpt_125m(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt_350m(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


def gpt_1p3b(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                     max_position_embeddings=2048, **kw)


def gpt_13b(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=5120, num_layers=40, num_heads=40,
                     max_position_embeddings=2048, **kw)


# -- pipeline-parallel variant -------------------------------------------

def _tied_head_forward(shared_emb, x):
    """LM head applied with the shared embedding's weight (reference:
    SharedLayerDesc weight tying across first/last pp stage)."""
    w = shared_emb.word_embeddings.weight
    if mp_active():
        x = _c_identity(x)
    return ops.matmul(x, w, transpose_y=True)


class GPTForCausalLMPipe:
    """Builds the PipelineLayer form of GPTForCausalLM.

    (reference: PaddleNLP GPTForCausalLMPipe / reference pp_layers.py:261
    PipelineLayer usage — LayerDesc list with SharedLayerDesc embedding
    tying; here the homogeneous GPTDecoderLayer run becomes the
    stacked/scanned pipelined middle.)

    Use as ``model = GPTForCausalLMPipe(config)`` — returns a
    PipelineLayer with loss_fn=GPTPretrainingCriterion, ready for
    ``fleet.distributed_model`` + ``train_batch``.
    """

    def __new__(cls, config: GPTConfig, num_stages=None,
                recompute_interval: int = 0, **pp_kwargs):
        from ..distributed.fleet.meta_parallel import (LayerDesc,
                                                       PipelineLayer,
                                                       SharedLayerDesc)

        descs = [
            SharedLayerDesc("embed", GPTEmbeddings, None, "weight", config),
            *[LayerDesc(GPTDecoderLayer, config)
              for _ in range(config.num_layers)],
            LayerDesc(LayerNorm, config.hidden_size,
                      epsilon=config.layer_norm_eps),
        ]
        if config.tie_word_embeddings:
            descs.append(SharedLayerDesc("embed", GPTEmbeddings,
                                         _tied_head_forward, "weight",
                                         config))
        else:
            descs.append(LayerDesc(
                ColumnParallelLinear, config.hidden_size, config.vocab_size,
                weight_attr=_init_attr(config.initializer_range),
                has_bias=False, gather_output=False))
        model = PipelineLayer(
            layers=descs, num_stages=num_stages,
            loss_fn=GPTPretrainingCriterion(config),
            seg_method="layer:GPTDecoderLayer",
            recompute_interval=recompute_interval, **pp_kwargs)
        if config.dtype not in ("float32", None):
            model.astype(config.dtype)
        # expose the model config on the PipelineLayer like the eager
        # GPTForCausalLM does: the engine's flop accountant (MFU) and
        # the memory ledger's state accounting / auto_tuner cross-check
        # (observability/memledger.py) read layer geometry from it
        model.config = config
        return model


__all__.append("GPTForCausalLMPipe")


def gpt_moe_tiny(**kw) -> GPTConfig:
    kw.setdefault("num_experts", 4)
    return gpt_tiny(**kw)


def ernie_moe_base(**kw) -> GPTConfig:
    """ERNIE-MoE style base config (BASELINE.md EP benchmark row)."""
    kw.setdefault("num_experts", 64)
    kw.setdefault("moe_every", 2)
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)
