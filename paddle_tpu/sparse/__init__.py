"""Sparse tensors (paddle.sparse analog).

(reference: python/paddle/sparse/ — creation.py sparse_coo_tensor:34,
sparse_csr_tensor:159; C++ phi/core/sparse_coo_tensor.h,
sparse_csr_tensor.h; kernels phi/kernels/sparse/.)

TPU-native: the storage/compute substrate is ``jax.experimental.sparse``
BCOO — XLA's batched-COO format whose matmuls lower to gather/segment-
sum programs the TPU runs well, instead of cuSPARSE dynload. A
SparseTensor wraps one BCOO and interops with dense Tensors
(``to_dense``/``matmul``/elementwise); CSR inputs are accepted and
converted (BCOO is the single canonical layout on XLA — the analog of
the reference keeping COO/CSR distinct for cuSPARSE's sake).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..tensor import Tensor

__all__ = ["SparseTensor", "CsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "is_sparse", "add", "multiply", "matmul",
           "masked_matmul", "relu", "transpose", "to_dense"]


class SparseTensor:
    """COO sparse tensor over jax BCOO."""

    def __init__(self, bcoo: jsparse.BCOO, stop_gradient: bool = True):
        self._bcoo = bcoo
        self.stop_gradient = stop_gradient

    # -- reference API surface -----------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T, stop_gradient=True)

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data, stop_gradient=True)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense(),
                      stop_gradient=self.stop_gradient)

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def to_sparse_coo(self, sparse_dim=None) -> "SparseTensor":
        return self

    def to_sparse_csr(self) -> "CsrTensor":
        return CsrTensor(jsparse.BCSR.from_bcoo(
            jsparse.bcoo_sum_duplicates(self._bcoo)),
            stop_gradient=self.stop_gradient)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _dense_val(x):
    if isinstance(x, SparseTensor):
        return x._bcoo.todense()
    if isinstance(x, CsrTensor):
        return x._bcsr.todense()
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseTensor:
    """indices: [ndim, nnz] (reference creation.py:34)."""
    idx = np.asarray(getattr(indices, "_value", indices))
    val = jnp.asarray(getattr(values, "_value", values))
    if dtype is not None:
        val = val.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseTensor(bcoo, stop_gradient=stop_gradient)


class CsrTensor:
    """CSR sparse matrix over jax BCSR (reference:
    phi/core/sparse_csr_tensor.h:32 — crows/cols/values; kernels
    phi/kernels/sparse/ csr family). BCSR's dot_general lowers to the
    same gather/segment-sum XLA programs as BCOO, so CSR here is a
    first-class LAYOUT (row-slice friendly, the reference's
    crows()/cols() surface) rather than a distinct kernel backend."""

    def __init__(self, bcsr: "jsparse.BCSR", stop_gradient: bool = True):
        self._bcsr = bcsr
        self.stop_gradient = stop_gradient

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    @property
    def nnz(self) -> int:
        return int(self._bcsr.nse)

    def crows(self) -> Tensor:
        return Tensor(self._bcsr.indptr, stop_gradient=True)

    def cols(self) -> Tensor:
        return Tensor(self._bcsr.indices, stop_gradient=True)

    def values(self) -> Tensor:
        return Tensor(self._bcsr.data, stop_gradient=True)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcsr.todense(),
                      stop_gradient=self.stop_gradient)

    def to_sparse_coo(self, sparse_dim=None) -> SparseTensor:
        return SparseTensor(self._bcsr.to_bcoo(),
                            stop_gradient=self.stop_gradient)

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def to_sparse_csr(self) -> "CsrTensor":
        return self

    def __repr__(self):
        return (f"CsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> CsrTensor:
    """CSR from components (reference creation.py:159)."""
    crows = jnp.asarray(getattr(crows, "_value", crows), jnp.int32)
    cols = jnp.asarray(getattr(cols, "_value", cols), jnp.int32)
    val = jnp.asarray(getattr(values, "_value", values))
    if dtype is not None:
        val = val.astype(dtype)
    bcsr = jsparse.BCSR((val, cols, crows), shape=tuple(shape))
    return CsrTensor(bcsr, stop_gradient=stop_gradient)


def is_sparse(x) -> bool:
    return isinstance(x, (SparseTensor, CsrTensor))


def to_dense(x) -> Tensor:
    return x.to_dense() if isinstance(x, (SparseTensor, CsrTensor)) else x


# -- ops (reference python/paddle/sparse/binary.py, unary.py) -----------


def add(x: SparseTensor, y) -> SparseTensor:
    if isinstance(y, SparseTensor):
        data = jnp.concatenate([x._bcoo.data, y._bcoo.data])
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices], axis=0)
        out = jsparse.bcoo_sum_duplicates(
            jsparse.BCOO((data, idx), shape=x._bcoo.shape))
        return SparseTensor(out)
    return SparseTensor(
        jsparse.BCOO.fromdense(x._bcoo.todense() + _dense_val(y)))


def multiply(x: SparseTensor, y) -> SparseTensor:
    if isinstance(y, SparseTensor):
        return SparseTensor(jsparse.BCOO.fromdense(
            x._bcoo.todense() * y._bcoo.todense()))
    # dense factor: scale the stored values (sparsity preserved)
    yv = _dense_val(y)
    taken = yv[tuple(x._bcoo.indices.T)] if yv.ndim else yv
    return SparseTensor(jsparse.BCOO((x._bcoo.data * taken,
                                      x._bcoo.indices),
                                     shape=x._bcoo.shape))


def matmul(x, y) -> Tensor:
    """sparse @ dense (or dense @ sparse) -> dense (reference
    sparse/binary.py matmul over cusparse spmm/spgemm; COO and CSR)."""
    xs, ys = is_sparse(x), is_sparse(y)
    if xs and not ys:
        op = x._bcsr if isinstance(x, CsrTensor) else x._bcoo
        return Tensor(op @ _dense_val(y))
    if ys and not xs:
        if isinstance(y, CsrTensor):
            # dense @ csr through the structured BCOO dot (no
            # densification of the sparse operand)
            return Tensor(_dense_val(x) @ y._bcsr.to_bcoo())
        return Tensor(_dense_val(x) @ y._bcoo)
    if xs and ys:
        return Tensor(_dense_val(x) @ _dense_val(y))
    raise TypeError("matmul expects at least one sparse tensor")


def masked_matmul(x, y, mask):
    """dense @ dense evaluated ONLY at mask's nonzeros (reference
    sparse/binary.py masked_matmul / cusparse SDDMM). The output takes
    the mask's layout (COO mask -> COO out, CSR mask -> CSR out)."""
    xv, yv = _dense_val(x), _dense_val(y)
    if isinstance(mask, CsrTensor):
        crows, cols = mask._bcsr.indptr, mask._bcsr.indices
        rows = jnp.repeat(jnp.arange(len(crows) - 1),
                          jnp.diff(crows),
                          total_repeat_length=int(mask._bcsr.nse))
        vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
        return CsrTensor(jsparse.BCSR((vals, cols, crows),
                                      shape=tuple(mask.shape)))
    idx = mask._bcoo.indices
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def relu(x: SparseTensor) -> SparseTensor:
    return SparseTensor(jsparse.BCOO(
        (jnp.maximum(x._bcoo.data, 0), x._bcoo.indices),
        shape=x._bcoo.shape))


def transpose(x: SparseTensor, perm: Sequence[int]) -> SparseTensor:
    return SparseTensor(jsparse.bcoo_transpose(
        x._bcoo, permutation=tuple(perm)))
