"""Build-path introspection (reference: python/paddle/sysconfig.py)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of the package's C headers (csrc ships sources; the
    built shared objects live in lib/)."""
    return os.path.join(_ROOT, "include")


def get_lib():
    """Directory containing the package's native shared libraries
    (tcp_store / shm_ring builds)."""
    return os.path.join(_ROOT, "lib")
