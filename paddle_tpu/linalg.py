"""paddle.linalg namespace (reference: python/paddle/linalg.py)."""
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import __all__  # noqa: F401
from .ops.math import cross, dot, kron, norm, outer  # noqa: F401
