"""paddle.linalg namespace (reference: python/paddle/linalg.py)."""
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import __all__ as _ops_all
from .ops.math import cross, dot, kron, norm, outer  # noqa: F401
from .ops.api_tail import lu_unpack, pca_lowrank  # noqa: F401


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """(reference: tensor/linalg.py vector_norm) — always the
    ELEMENTWISE vector norm: with axis=None the tensor flattens first
    (ops.math.norm would route p=inf on 2-D into the matrix norm)."""
    from .ops import math as _m

    if axis is None and x.ndim > 1:
        x = x.reshape([-1])
    return _m.norm(x, p=float(p), axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """(reference: tensor/linalg.py matrix_norm)."""
    from .ops import math as _m

    return _m.norm(x, p=p, axis=tuple(axis), keepdim=keepdim)


__all__ = list(_ops_all) + ["cross", "dot", "kron", "norm", "outer",
                            "lu_unpack", "pca_lowrank", "vector_norm",
                            "matrix_norm"]
