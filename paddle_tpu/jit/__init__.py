"""JIT: whole-graph XLA compilation of eager code.

(reference: python/paddle/jit/ — dy2static AST transpiler + SOT bytecode
JIT at jit/sot/; api.py:135 ``to_static``. The TPU-native replacement is
radically simpler: because every eager op is a traceable JAX call and the
autograd tape records through Tracers, ``to_static`` just wraps the
function in jax.jit — forward, backward(), and optimizer.step() all trace
into ONE fused XLA program. No bytecode analysis needed; Python control
flow is handled by tracing per input-shape like SOT's guard system,
falling back to retrace on new shapes.)
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import numpy as np
import jax.numpy as jnp

from ..tensor import Parameter, Tensor
from ..nn.layer import Layer

__all__ = ["to_static", "not_to_static", "TracedStep", "save", "load"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_arraylike(x):
    return isinstance(x, (Tensor, jax.Array, np.ndarray))


class TracedStep:
    """Compile an eager train/eval step into a single XLA executable.

    The wrapped function may freely mutate Parameters (optimizer updates,
    BN running stats): all Tensors reachable from ``trackables`` are treated
    as implicit state — passed in as traced inputs and their new values
    returned as traced outputs, then written back. This is the
    donate-buffers functional fixpoint the reference gets from its static
    graph Program, achieved here without one.
    """

    def __init__(self, fn: Callable, trackables=None, donate_state: bool = True):
        self._fn = fn
        self._trackables = trackables or []
        self._donate = donate_state
        self._compiled = {}
        self._state_tensors: Optional[list] = None

    def _collect_state(self):
        tensors = []
        seen = set()

        def add(t):
            if isinstance(t, Tensor) and id(t) not in seen:
                seen.add(id(t))
                tensors.append(t)

        for obj in self._trackables:
            if isinstance(obj, Layer):
                for _, p in obj.named_parameters():
                    add(p)
                for _, b in obj.named_buffers():
                    add(b)
            elif isinstance(obj, Tensor):
                add(obj)
            elif hasattr(obj, "_parameter_list"):  # Optimizer
                opt = obj
                for p in (opt._parameter_list or []):
                    add(p)
        return tensors

    def __call__(self, *args, **kwargs):
        from ..core import rng

        if self._state_tensors is None:
            self._state_tensors = self._collect_state()
        state_tensors = self._state_tensors

        # optimizer states live outside tensors; snapshot via closure below
        opts = [o for o in self._trackables if hasattr(o, "_states")]

        def pure_step(state_values, opt_states, rng_seed, arg_values):
            # install traced values into the real objects, run, harvest
            old = [t._value for t in state_tensors]
            old_states = [dict(o._states) for o in opts]
            for t, v in zip(state_tensors, state_values):
                t._value = v
            for o, s in zip(opts, opt_states):
                o._states = dict(s)
            try:
                with rng.fork_traced(rng_seed):
                    wrapped = jax.tree_util.tree_map(
                        lambda x: Tensor(x) if isinstance(
                            x, (jax.Array, jax.core.Tracer)) else x,
                        arg_values)
                    out = self._fn(*wrapped[0], **wrapped[1])
                new_state = [t._value for t in state_tensors]
                new_opt_states = [dict(o._states) for o in opts]
                out_vals = jax.tree_util.tree_map(
                    _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor))
                return out_vals, new_state, new_opt_states
            finally:
                for t, v in zip(state_tensors, old):
                    t._value = v
                for o, s in zip(opts, old_states):
                    o._states = s

        key = "default"
        if key not in self._compiled:
            self._compiled[key] = jax.jit(pure_step)
        arg_values = jax.tree_util.tree_map(
            _unwrap, (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        state_values = [t._value for t in state_tensors]
        opt_states = [dict(o._states) for o in opts]
        seed = rng.get_key()
        seed32 = jax.random.randint(seed, (), 0, 2**31 - 1, jnp.int32).astype(
            jnp.uint32)
        try:
            out_vals, new_state, new_opt_states = self._compiled[key](
                state_values, opt_states, seed32, arg_values)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError) as e:
            raise TypeError(
                "to_static cannot trace Python control flow over a "
                "TENSOR VALUE (an `if tensor:` / `while tensor:` / "
                "`int(tensor)` inside the compiled function). Rewrite "
                "the branch with paddle_tpu.static.nn.cond / while_loop "
                "/ switch_case (lax-backed, traceable), or move the "
                "data-dependent branch outside the compiled step. "
                f"Original error: {e}") from e
        for t, v in zip(state_tensors, new_state):
            t._value = v
        for o, s in zip(opts, new_opt_states):
            o._states = s
        return jax.tree_util.tree_map(
            lambda v: Tensor(v) if isinstance(v, jax.Array) else v, out_vals)


_to_static_enabled = [True]


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, trackables=None, **kwargs):
    """paddle.jit.to_static analog: returns a compiled callable.

    For a Layer, wraps its forward (inference-style). For a function that
    mutates state (train step), pass ``trackables=[model, optimizer]`` so
    state threading is handled (see TracedStep).
    """

    def deco(fn):
        if not _to_static_enabled[0]:
            return fn  # global toggle off: run eagerly (reference
            # jit/api.py enable_to_static contract)
        if isinstance(fn, Layer):
            layer = fn
            inner_forward = layer.forward
            step = TracedStep(lambda *a, **k: inner_forward(*a, **k),
                              trackables=[layer] + list(trackables or []))
            layer._traced_call = step
            layer.forward = step  # instance attr shadows the method
            return layer
        return TracedStep(fn, trackables=trackables)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    return fn


def save(layer, path, input_spec=None, **configs):
    """Export a deployable inference artifact (reference: jit/api.py
    ``paddle.jit.save`` → TranslatedLayer program + params; C++
    jit::Layer loads it without Python).

    TPU-native format: ``path + '.pdmodel'`` holds the serialized
    StableHLO export of the traced forward (jax.export — loadable with
    NO model code), ``path + '.pdparams'`` the state_dict. ``input_spec``
    (paddle.static.InputSpec list) fixes the signature; ``None`` dims
    become symbolic so the exported program accepts any batch size.
    """
    import os

    from ..framework.io import save as fsave
    from ..static import InputSpec

    enforce_layer = isinstance(layer, Layer)
    if not enforce_layer:
        raise TypeError("jit.save expects a Layer")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    fsave(layer.state_dict(), path + ".pdparams")

    if input_spec is None:
        fn = getattr(layer, "_traced_call", None)
        raise ValueError(
            "jit.save needs input_spec=[InputSpec(shape, dtype), ...] "
            "to export the program (None dims = dynamic)")

    params = list(layer.parameters())
    buffers = list(layer.buffers())
    state = params + buffers
    from ..distributed.engine import bind_params
    from ..autograd import no_grad

    def pure(state_vals, *inputs):
        with no_grad(), bind_params(state, state_vals):
            out = layer(*[Tensor(i, stop_gradient=True) for i in inputs])
        return jax.tree_util.tree_map(
            _unwrap, out, is_leaf=lambda x: isinstance(x, Tensor))

    # symbolic dims for None entries (dynamic batch)
    import jax.export  # attr-only access fails before the submodule import

    sym_names = iter("bcdefghij")
    scopes = jax.export.SymbolicScope()
    in_specs = []
    for spec in input_spec:
        if not isinstance(spec, InputSpec):
            spec = InputSpec.from_tensor(spec)
        dims = []
        for dim in spec.shape:
            if dim is None or (isinstance(dim, int) and dim < 0):
                dims.append(jax.export.symbolic_shape(
                    next(sym_names), scope=scopes)[0])
            else:
                dims.append(dim)
        in_specs.append(jax.ShapeDtypeStruct(tuple(dims), spec.dtype))
    state_specs = [jax.ShapeDtypeStruct(v._value.shape, v._value.dtype)
                   for v in state]
    exported = jax.export.export(jax.jit(pure))(state_specs, *in_specs)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())


class TranslatedLayer(Layer):
    """A loaded inference program (reference: TranslatedLayer in
    jit/translated_layer.py; C++ jit::Layer). Executes the serialized
    StableHLO export — no original model code needed."""

    def __init__(self, exported, state_dict):
        super().__init__()
        self._exported = exported
        # keep insertion order: params first, buffers after (matches save)
        self._state_vals = [v._value if isinstance(v, Tensor) else
                            jnp.asarray(v) for v in state_dict.values()]
        self._state_keys = list(state_dict.keys())
        for k, v in state_dict.items():
            self.add_parameter(k.replace(".", "__"),
                               Parameter(v._value if isinstance(v, Tensor)
                                         else jnp.asarray(v),
                                         trainable=False))

    def forward(self, *inputs):
        vals = [i._value if isinstance(i, Tensor) else jnp.asarray(i)
                for i in inputs]
        out = self._exported.call(self._state_vals, *vals)
        return jax.tree_util.tree_map(
            lambda v: Tensor(v, stop_gradient=True), out)


def load(path, **configs):
    """Load a jit.save artifact as a callable TranslatedLayer; falls back
    to returning the raw state_dict when only params were saved."""
    import os

    from ..framework.io import load as fload

    state = fload(path + ".pdparams")
    model_file = path + ".pdmodel"
    if not os.path.exists(model_file):
        return state
    import jax.export  # attr-only access fails before the submodule import

    with open(model_file, "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    return TranslatedLayer(exported, state)


# jit API tail (reference: python/paddle/jit/__init__.py)


def enable_to_static(flag: bool):
    """Globally toggle to_static compilation (reference: jit/api.py
    enable_to_static — with it off, to_static returns the eager fn)."""
    _to_static_enabled[0] = bool(flag)


_ignored_modules = []


def ignore_module(modules):
    """(reference: jit/api.py ignore_module) — modules whose calls the
    tracer should not compile. jax tracing has no bytecode translation
    layer, so this only records intent."""
    _ignored_modules.extend(modules if isinstance(modules, list)
                            else [modules])


def set_code_level(level=100, also_to_stdout=False):
    """(reference: jit/dy2static logging) — no transpiled code exists
    here (tracing, not source translation); accepted for parity."""


def set_verbosity(level=0, also_to_stdout=False):
    pass


__all__ = __all__ + ["enable_to_static", "ignore_module",
                     "set_code_level", "set_verbosity"]
