"""Multi-replica serving front door: admission routing + phase placement.

One :class:`Router` fronts N :class:`~.serving.ServingEngine` replicas
— unified, or split by phase into prefill and decode pools (the
disaggregated topology of inference/disagg.py). Placement for a new
request walks three signals in order:

1. **Health** — replicas reporting ``health() == "degraded"`` (shedding
   load / queue at bound), or flagged degraded by an attached
   :class:`~..observability.fleet.FleetCollector` overlay (unreachable
   / stale / member-reported), are skipped while any healthy candidate
   exists; a fully-degraded pool still serves (shedding beats
   blackholing).
2. **Prefix affinity** — the prompt's page-aligned rolling prefix
   hashes (the SAME hashes the prefix cache registers pages under) are
   matched against each candidate's cache; the replica already holding
   the longest prefix run wins, so shared-prefix traffic lands where
   its KV already lives instead of recomputing it cold.
3. **Least-loaded** — otherwise the shortest (queue + active rows,
   most free pages) replica wins.

Every placement increments
``paddle_tpu_router_requests_total{replica, decision}`` and each tick
sets ``paddle_tpu_router_phase_slots{phase}`` to the live row count
per phase, so a dashboard sees both the steering and the fleet shape.

Tracing: the router mints one W3C trace per request (or adopts the
client's ``traceparent``) and hands the SAME header to every engine
hop — initial placement, migration (the engines stitch via the
exported trace identity), and crc/refusal retries — so the per-replica
Chrome traces stitch into one cross-replica timeline on ``trace_id``.

The HTTP front door (:class:`RouterServer`) follows the observability
stack's stdlib-only server idiom: handler threads never touch the
engines — ``POST /v1/generate`` enqueues onto a thread-safe inbox and
blocks on a per-request Event; the single serving loop
(:meth:`Router.step`, driven by the caller or :meth:`Router.run`)
drains the inbox, places, steps every replica, pumps migrations, and
completes the pending events. The engines keep their single-driver
discipline with zero locks added.
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.enforce import enforce
from ..observability.catalog import serving_metrics as _serving_metrics
from ..observability.spans import (format_traceparent, make_span_id,
                                   make_trace_id)
from .disagg import KVMigrator

__all__ = ["Replica", "Router", "RouterServer"]


class Replica:
    """One named engine behind the front door. The router reads its
    health, load, and prefix cache through the in-process handle; the
    same signals are scrapeable cross-host via the FleetCollector
    overlay (observability/fleet.py)."""

    def __init__(self, name: str, engine):
        self.name = str(name)
        self.engine = engine

    @property
    def phase(self) -> str:
        return self.engine.phase or "unified"

    def load(self) -> Tuple[int, int]:
        """Ordering key: fewest queued+active rows first, then most
        free pages (negated)."""
        eng = self.engine
        return (len(eng.queue) + eng.num_active, -eng._avail_pages())


class _Pending:
    """One blocked HTTP request: handler thread fills it in, parks on
    ``done``; the serving loop completes it."""

    __slots__ = ("body", "traceparent", "done", "result", "error")

    def __init__(self, body: Dict[str, Any],
                 traceparent: Optional[str]):
        self.body = body
        self.traceparent = traceparent
        self.done = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None


class Router:
    """The placement brain + serving loop over a replica fleet.

    ``replicas`` is ``[(name, engine), ...]``. Prefill-phase replicas
    require at least one decode-phase replica to stream to; the router
    then owns a :class:`KVMigrator` and pumps it every step. An
    attached ``collector`` (FleetCollector) overlays cross-host health
    on the in-process signal — a member it calls degraded is skipped
    exactly like one whose engine says so."""

    def __init__(self, replicas: Sequence[Tuple[str, Any]],
                 collector=None, affinity: bool = True):
        self._metrics = _serving_metrics()
        self.replicas = [Replica(n, e) for n, e in replicas]
        enforce(self.replicas, "Router needs at least one replica")
        enforce(len({r.name for r in self.replicas})
                == len(self.replicas),
                "replica names must be unique — they key placement "
                "counters and the gid map")
        self._by_name = {r.name: r for r in self.replicas}
        self._name_of = {id(r.engine): r.name for r in self.replicas}
        # the admission pool: anything that can run a prefill
        self.frontdoor = [r for r in self.replicas
                          if r.engine.phase != "decode"]
        enforce(self.frontdoor,
                "Router needs a prefill-capable (phase None or "
                '"prefill") replica to admit prompts into')
        prefill = [r.engine for r in self.replicas
                   if r.engine.phase == "prefill"]
        decode = [r.engine for r in self.replicas
                  if r.engine.phase == "decode"]
        if prefill:
            enforce(decode, 'phase="prefill" replicas park every '
                    'request for migration; the fleet needs a '
                    'phase="decode" replica to stream KV pages to')
        self._prefill_engines = prefill
        self.migrator = KVMigrator(decode) if decode else None
        self.collector = collector
        self.affinity = bool(affinity)
        self._next_gid = 0
        # gid -> {"replica", "rid", "traceparent", "prompt", ...} while
        # in flight; resolved requests move to _results
        self._placed: Dict[int, Dict[str, Any]] = {}
        self._results: Dict[int, Any] = {}
        # HTTP front door plumbing: handler threads put _Pending here
        # (thread-safe Queue); ONLY the serving loop drains it
        self._inbox: "queue.Queue[_Pending]" = queue.Queue()
        self._http_pending: Dict[int, _Pending] = {}

    # -- placement -------------------------------------------------------

    def _healthy(self, r: Replica) -> bool:
        if r.engine.health() != "ok":
            return False
        if self.collector is not None:
            if self.collector.member_health(r.name)["status"] != "ok":
                return False
        return True

    def _place(self, prompt: np.ndarray,
               exclude: Optional[str] = None) -> Tuple[Replica, str]:
        """Pick the replica for one prompt: health filter, then prefix
        affinity, then least-loaded."""
        pool = [r for r in self.frontdoor if r.name != exclude] \
            or self.frontdoor
        cands = [r for r in pool if self._healthy(r)] or pool
        if self.affinity and len(cands) > 1:
            best, best_run = None, 0
            for r in cands:
                eng = r.engine
                if not eng.prefix:
                    continue
                run = eng.prefix_match(eng._prefix_hashes(prompt))
                if run > best_run:
                    best, best_run = r, run
            if best is not None:
                return best, "affinity"
        return min(cands, key=Replica.load), "least_loaded"

    # -- submission ------------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               traceparent: Optional[str] = None) -> int:
        """Place one request on the fleet; returns its global id. The
        router-level trace identity (minted here unless the caller
        sent a ``traceparent``) follows the request across every
        replica hop, including retries."""
        gid = self._next_gid
        self._next_gid += 1
        if traceparent is None:
            traceparent = format_traceparent(make_trace_id(),
                                             make_span_id())
        arr = np.asarray(prompt, np.int64).reshape(-1)
        r, decision = self._place(arr)
        rid = r.engine.submit(arr, max_new_tokens=max_new_tokens,
                              eos_token_id=eos_token_id,
                              trace_id=traceparent)
        self._metrics["router_requests"].inc(replica=r.name,
                                             decision=decision)
        self._placed[gid] = {
            "replica": r.name, "rid": rid, "traceparent": traceparent,
            "prompt": arr, "max_new_tokens": max_new_tokens,
            "eos_token_id": eos_token_id,
        }
        return gid

    def _retry(self, gid: int, info: Dict[str, Any]) -> None:
        """Resubmit after a corrupt/refused migration, preferring a
        replica other than the one the request just failed on. Greedy
        prefill restart recommits the same tokens, so the retry is
        exact; the original trace identity rides along."""
        rec = self._placed[gid]
        r, _ = self._place(rec["prompt"], exclude=rec["replica"])
        rid = r.engine.submit(rec["prompt"],
                              max_new_tokens=rec["max_new_tokens"],
                              eos_token_id=rec["eos_token_id"],
                              trace_id=rec["traceparent"])
        self._metrics["router_requests"].inc(replica=r.name,
                                             decision="retry")
        rec["replica"] = r.name
        rec["rid"] = rid

    # -- the serving loop ------------------------------------------------

    def _gid_at(self, engine, rid: int) -> Optional[int]:
        name = self._name_of[id(engine)]
        for gid, rec in self._placed.items():
            if rec["replica"] == name and rec["rid"] == rid:
                return gid
        return None

    def _on_migration(self, ev: Dict[str, Any]) -> None:
        gid = self._gid_at(ev["src"], ev["src_rid"])
        if gid is None:       # directly-submitted (non-router) request
            return
        if ev["status"] == "ok":
            rec = self._placed[gid]
            rec["replica"] = self._name_of[id(ev["dst"])]
            rec["rid"] = ev["dst_rid"]
        else:                 # crc_error / refused: restart from scratch
            self._retry(gid, ev.get("request", {}))

    def _drain_http(self) -> None:
        while True:
            try:
                p = self._inbox.get_nowait()
            except queue.Empty:
                return
            try:
                gid = self.submit(
                    p.body["prompt"],
                    max_new_tokens=p.body.get("max_new_tokens"),
                    eos_token_id=p.body.get("eos_token_id"),
                    traceparent=p.traceparent)
            except Exception as e:   # malformed body fails ONE request
                p.error = str(e)
                p.done.set()
                continue
            self._http_pending[gid] = p

    def _collect(self) -> None:
        for gid, rec in list(self._placed.items()):
            eng = self._by_name[rec["replica"]].engine
            req = eng.finished.get(rec["rid"])
            if req is None:
                continue
            self._results[gid] = req
            del self._placed[gid]
            p = self._http_pending.pop(gid, None)
            if p is not None:
                p.result = {
                    "gid": gid,
                    "tokens": [int(t) for t in req.new_tokens],
                    "shed_reason": req.shed_reason,
                    "trace_id": req.trace_id,
                    "traceparent": req.traceparent,
                }
                p.done.set()

    def _note_tick(self) -> None:
        occ: Dict[str, int] = {}
        for r in self.replicas:
            occ[r.phase] = occ.get(r.phase, 0) + r.engine.num_active
        for ph, n in occ.items():
            self._metrics["phase_slots"].set(n, phase=ph)

    def step(self) -> None:
        """One fleet tick: drain the HTTP inbox, step every replica,
        pump migrations, collect finished requests, note gauges."""
        self._drain_http()
        for r in self.replicas:
            r.engine.step()
        if self.migrator is not None:
            for ev in self.migrator.pump(self._prefill_engines):
                self._on_migration(ev)
        self._collect()
        self._note_tick()

    def run(self, max_steps: Optional[int] = None) -> Dict[int, Any]:
        """Step until every placed request finishes (or ``max_steps``);
        returns {gid: ServingRequest}."""
        steps = 0
        while self._placed:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return dict(self._results)

    def result(self, gid: int):
        """The finished ServingRequest for ``gid`` (None while in
        flight)."""
        return self._results.get(gid)

    @property
    def pending(self) -> int:
        return len(self._placed) + self._inbox.qsize()

    # -- introspection ---------------------------------------------------

    def healthz(self) -> Dict[str, Any]:
        """The fleet rollup a load balancer polls: degraded when any
        replica is (matching the engines' /healthz contract)."""
        reps: Dict[str, Any] = {}
        n_bad = 0
        for r in self.replicas:
            h = r.engine.health()
            if self.collector is not None:
                overlay = self.collector.member_health(r.name)
                if overlay["status"] != "ok":
                    h = "degraded"
            n_bad += h != "ok"
            reps[r.name] = {
                "phase": r.phase, "health": h,
                "active": r.engine.num_active,
                "queued": len(r.engine.queue),
                "free_pages": r.engine._avail_pages(),
            }
        return {"status": "degraded" if n_bad else "ok",
                "replicas": reps, "pending": len(self._placed)}

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "submitted": self._next_gid,
            "in_flight": len(self._placed),
            "finished": len(self._results),
        }
        if self.migrator is not None:
            out["migrated"] = self.migrator.migrated
            out["migration_wire_bytes"] = self.migrator.wire_bytes
        return out


class RouterServer:
    """stdlib-HTTP front door over a :class:`Router`.

    ``POST /v1/generate`` with ``{"prompt": [ids...],
    "max_new_tokens": n}`` (optional ``traceparent`` header) blocks
    until the fleet finishes the request, then returns its tokens and
    trace identity. ``GET /healthz`` returns the fleet rollup, ``GET
    /stats`` the placement counters. Handler threads only enqueue and
    wait — the caller keeps driving ``router.step()`` (or
    :meth:`serve_pending`), preserving the engines' single-driver
    discipline."""

    def __init__(self, router: Router, port: int = 0,
                 host: str = "127.0.0.1", timeout_s: float = 120.0):
        self.router = router
        rt = router
        tmo = float(timeout_s)

        class _Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, obj: Dict[str, Any]) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, rt.healthz())
                elif self.path == "/stats":
                    self._reply(200, rt.stats())
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/generate":
                    self._reply(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._reply(400, {"error": "body is not JSON"})
                    return
                if "prompt" not in body:
                    self._reply(400, {"error": 'missing "prompt"'})
                    return
                pend = _Pending(body,
                                self.headers.get("traceparent"))
                rt._inbox.put(pend)
                if not pend.done.wait(tmo):
                    self._reply(504, {"error": "serving loop timeout"})
                    return
                if pend.error is not None:
                    self._reply(400, {"error": pend.error})
                    return
                self._reply(200, pend.result)

            def log_message(self, fmt, *args):
                pass

        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def serve_pending(self, max_steps: int = 10000) -> None:
        """Drive the serving loop until the inbox and fleet drain —
        the blocking companion to a burst of HTTP submissions."""
        steps = 0
        while self.router.pending and steps < max_steps:
            self.router.step()
            steps += 1

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "RouterServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
