"""Inference / serving API (paddle.inference analog).

TPU-native redesign of the reference's AnalysisPredictor stack
(reference: paddle/fluid/inference/api/analysis_predictor.h:100
AnalysisPredictor::Run, paddle_inference_api.h Config/CreatePredictor,
api/api_impl.cc NativePaddlePredictor). The reference predictor loads a
static Program, runs IR passes and executes on a Scope; every knob
about IR/memory optimization is owned here by XLA, so the TPU predictor
is: load params → jit-compile → run.

Serving design (the fused_multi_transformer decode loop, XLA style):

- ``Predictor.run`` — generic compiled forward, cached per input shape.
- ``Predictor.generate`` — LLM serving path over any model exposing the
  KV-cache protocol (``_empty_caches``/``forward(ids, caches, offset)``,
  e.g. LlamaForCausalLM, FusedMultiTransformer wrappers):
  * PREFILL: the prompt is right-padded to a power-of-two bucket so one
    compiled program serves every prompt length in the bucket (the
    garbage cache rows past the longest true length are never attended —
    decode masks by absolute position — and are overwritten as decoding
    advances); last-token logits are gathered at each row's true length.
  * RAGGED batches decode at PER-ROW offsets: each row's rope
    positions, cache-write slot, and attention frontier advance from
    its own true length, with optional per-row EOS stopping
    (GenerationConfig.eos_token_id) — the continuous-batching
    decode semantics of the reference's block_multi_head_attention.
  * PAGED KV (Config.enable_paged_kv): physical [page, D] pages in a
    shared pool + per-row block tables; pages are allocated per row
    for len+new tokens only, so a ragged batch pays sum(len_i), not
    B*max_len, of HBM (reference: phi/kernels/fusion/gpu/
    block_multi_head_attention_kernel.cu — there CUDA threads chase
    the table; here the Pallas BlockSpec index map does).
  * DECODE: the WHOLE token loop is ONE compiled XLA program — a
    ``lax.scan`` over steps carrying (token, caches, rng) with donated
    cache buffers, sampling (greedy/temperature/top-k/top-p) fused in.
    Zero host round-trips per token; the cache-KV attention inside is
    the Pallas decode kernel on TPU (ops/pallas/decode_attention.py).
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "GenerationConfig",
           "CompileStats", "ServingEngine", "ServingRequest",
           "Router", "RouterServer", "Replica", "KVMigrator",
           "MigrationCorruptError"]


# one lattice definition for the whole tree (serving S/P buckets, MoE
# expert capacity): core/bucketing.py
from ..core.bucketing import bucket as _bucket  # noqa: E402


# shared with the training engine (ParallelEngine.stats); the class
# lives in core so distributed/engine.py can import it without pulling
# the whole inference stack
from ..core.compile_stats import CompileStats  # noqa: E402,F401


def _sample(logits, key, gen: "GenerationConfig"):
    """Greedy / temperature / top-k / top-p sampling (traceable; used by
    both the first-token host step and the compiled decode loop)."""
    lg = logits.astype(jnp.float32)
    if gen.temperature and gen.temperature > 0:
        lg = lg / gen.temperature
        if gen.top_k:
            kth = jax.lax.top_k(lg, gen.top_k)[0][:, -1][:, None]
            lg = jnp.where(lg < kth, -1e30, lg)
        if gen.top_p < 1.0:
            srt = jnp.sort(lg, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # smallest set with cumulative prob >= top_p
            cutoff_idx = jnp.sum(cum < gen.top_p, axis=-1)
            cutoff = jnp.take_along_axis(srt, cutoff_idx[:, None], axis=-1)
            lg = jnp.where(lg < cutoff, -1e30, lg)
        return jax.random.categorical(key, lg, axis=-1)
    return jnp.argmax(lg, axis=-1)


@dataclass
class GenerationConfig:
    max_new_tokens: int = 128
    temperature: float = 0.0       # 0 = greedy
    top_k: int = 0                 # 0 = off
    top_p: float = 1.0             # 1 = off
    seed: int = 0
    eos_token_id: Optional[int] = None  # per-row stop; post-EOS tokens
    #                                     are filled with eos_token_id


class Config:
    """Predictor configuration (reference: paddle_inference_api.h Config).

    The TPU predictor takes either a live Layer (``set_model``) or a
    params file saved with ``paddle.save(model.state_dict(), path)``
    plus a model factory. The reference's IR/pass/memory knobs are
    accepted as no-ops for API compatibility — XLA owns those choices.
    """

    def __init__(self, model_dir: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.model_dir = model_dir
        self.params_file = params_file
        self._model = None
        self._model_factory: Optional[Callable[[], Any]] = None
        self.dtype: Optional[str] = None
        self.max_batch_size = 8
        self.max_length: Optional[int] = None
        self.generation = GenerationConfig()
        self._mem_optim = True
        self._ir_optim = True
        self._weight_only_algo: Optional[str] = None
        self._weight_only_skip = ("lm_head",)
        self._kv_page_size: Optional[int] = None

    # -- model sources --------------------------------------------------
    def set_model(self, model) -> "Config":
        """Serve a live Layer instance."""
        self._model = model
        return self

    def set_model_factory(self, factory: Callable[[], Any]) -> "Config":
        """Factory building the (uninitialized) model; combined with
        ``params_file`` / ``model_dir`` for weight loading."""
        self._model_factory = factory
        return self

    def set_params_file(self, path: str) -> "Config":
        self.params_file = path
        return self

    def enable_weight_only(self, algo: str = "weight_only_int8",
                           skip=("lm_head",)) -> "Config":
        """Serve with int8/int4 weights resident in HBM
        (nn.quant.quantize_for_serving): decode is weight-bandwidth
        bound, so tokens/s scales with the byte shrink. ``skip`` keeps
        named layers (default: the LM head) in full precision."""
        if algo not in ("weight_only_int8", "weight_only_int4"):
            raise ValueError(
                f"enable_weight_only supports weight_only_int8/int4, got "
                f"{algo!r} (llm.int8 is the functional nn.quant."
                f"llm_int8_linear, not a serving swap)")
        self._weight_only_algo = algo
        self._weight_only_skip = tuple(skip)
        return self

    def enable_paged_kv(self, page_size: int = 64) -> "Config":
        """Serve with a paged (block-table) KV cache (reference:
        block_multi_head_attention / enable_block_attn): physical pages
        are allocated per row for ceil((len+new)/page) tokens instead of
        B*max_len rows, so ragged batches don't pay max-length HBM. The
        attention is the block-table Pallas kernel on TPU
        (ops/pallas/decode_attention.py paged_decode_attention)."""
        if page_size < 8 or page_size % 8:
            raise ValueError("page_size must be a multiple of 8 (TPU "
                             f"sublane tiling), got {page_size}")
        self._kv_page_size = int(page_size)
        return self

    # -- reference-compat knobs (XLA owns these; kept as recorded flags)
    def enable_memory_optim(self, flag: bool = True) -> None:
        self._mem_optim = flag

    def switch_ir_optim(self, flag: bool = True) -> None:
        self._ir_optim = flag

    def set_cpu_math_library_num_threads(self, n: int) -> None:
        pass

    def enable_use_gpu(self, *a, **k) -> None:  # pragma: no cover
        raise ValueError("paddle_tpu serves on TPU; there is no GPU path")


def create_predictor(config: Config) -> "Predictor":
    """(reference: paddle_infer::CreatePredictor)"""
    return Predictor(config)


class Predictor:
    def __init__(self, config: Config):
        self.config = config
        self._model = self._build_model(config)
        self._model.eval()
        self._params = list(self._model.parameters())
        self._run_fns: Dict[Any, Any] = {}
        self._decode_fns: Dict[Any, Any] = {}
        self._prefill_fns: Dict[Any, Any] = {}
        self._last_outputs: List[np.ndarray] = []
        self._input_names = ["input_ids"]
        self.stats = CompileStats()

    @staticmethod
    def _build_model(config: Config):
        model = config._model
        if model is None:
            if config._model_factory is None:
                raise ValueError(
                    "Config needs set_model(layer) or set_model_factory "
                    "(+ params_file/model_dir) before create_predictor")
            model = config._model_factory()
        path = config.params_file
        if path is None and config.model_dir:
            for cand in ("model.pdparams", "params"):
                p = os.path.join(config.model_dir, cand)
                if os.path.exists(p):
                    path = p
                    break
        if path:
            from ..framework.io import load

            model.set_state_dict(load(path))
        if config.dtype:
            model.astype(config.dtype)
        if config._weight_only_algo:
            from ..nn.quant import quantize_for_serving

            quantize_for_serving(model, config._weight_only_algo,
                                 config._weight_only_skip)
        return model

    # ------------------------------------------------------------------
    # generic forward serving (AnalysisPredictor::Run)
    # ------------------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return [f"output_{i}" for i in range(len(self._last_outputs) or 1)]

    def run(self, inputs: List[Any]) -> List[np.ndarray]:
        """Compiled forward on a list of inputs; one XLA program per
        input-shape signature (the predictor analog of shape-keyed
        retrace in jit/__init__.py)."""
        vals = [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                for x in inputs]
        key = tuple((v.shape, str(v.dtype)) for v in vals)
        self.stats.note("run", key)
        if key not in self._run_fns:
            model, params = self._model, self._params
            from ..autograd import no_grad
            from ..distributed.engine import bind_params

            def fwd(pvals, *xs):
                with no_grad(), bind_params(params, pvals):
                    out = model(*[Tensor(x, stop_gradient=True)
                                  for x in xs])
                outs = out if isinstance(out, (list, tuple)) else (out,)
                return [o._value if isinstance(o, Tensor) else o
                        for o in outs]

            self._run_fns[key] = jax.jit(fwd)
        pvals = tuple(p._value for p in self._params)
        outs = self._run_fns[key](pvals, *vals)
        self._last_outputs = [np.asarray(o) for o in outs]
        return self._last_outputs

    # ------------------------------------------------------------------
    # LLM serving (fused_multi_transformer decode loop)
    # ------------------------------------------------------------------
    def _max_len(self, S0: int, n_new: int) -> int:
        if self.config.max_length:
            return self.config.max_length
        cap = getattr(getattr(self._model, "config", None),
                      "max_position_embeddings", None)
        need = _bucket(S0) + n_new
        return min(cap, _bucket(need)) if cap else _bucket(need)

    def _prefill_fn(self, B, Sb, M):
        key = (B, Sb, M, self.config._kv_page_size)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        model, params = self._model, self._params
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def prefill(pvals, ids, caches, lengths):
            with no_grad(), bind_params(params, pvals):
                logits, caches = model.forward(
                    Tensor(ids, stop_gradient=True), caches=caches,
                    offset=0)
            lv = logits._value if isinstance(logits, Tensor) else logits
            # gather each row's logits at its true last prompt token
            last = jnp.take_along_axis(
                lv, (lengths - 1)[:, None, None], axis=1)[:, 0]
            return last, caches

        self._prefill_fns[key] = jax.jit(prefill, donate_argnums=(2,))
        return self._prefill_fns[key]

    def _decode_fn(self, B, M, n_new, gen: GenerationConfig, ragged,
                   paged):
        key = (B, M, n_new, gen.temperature, gen.top_k, gen.top_p,
               gen.eos_token_id, ragged, paged)
        if key in self._decode_fns:
            return self._decode_fns[key]
        model, params = self._model, self._params
        eos = gen.eos_token_id
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def decode(pvals, tok0, caches, pos0, rng):
            done0 = (tok0 == eos) if eos is not None \
                else jnp.zeros((B,), bool)

            def body(carry, _):
                tok, caches, pos, rng, done = carry
                with no_grad(), bind_params(params, pvals):
                    logits, caches = model.forward(
                        Tensor(tok[:, None], stop_gradient=True),
                        caches=caches, offset=pos)
                lv = (logits._value if isinstance(logits, Tensor)
                      else logits)
                rng, sub = jax.random.split(rng)
                nxt = _sample(lv[:, -1], sub, gen)
                if eos is not None:  # per-row stop: freeze at eos
                    nxt = jnp.where(done, jnp.asarray(eos, nxt.dtype),
                                    nxt)
                    done = done | (nxt == eos)
                return (nxt, caches, pos + 1, rng, done), nxt

            (tok, caches, _, _, _), toks = lax.scan(
                body, (tok0, caches, pos0, rng, done0), None,
                length=n_new)
            return jnp.swapaxes(toks, 0, 1), caches  # [B, n_new]

        self._decode_fns[key] = jax.jit(decode, donate_argnums=(2,))
        return self._decode_fns[key]

    # -- paged KV-cache pool (reference: block_multi_head_attention's
    #    block tables; here a host-side bump allocator + trash page) ---
    def _paged_caches(self, lengths, n_new, M, page, dtype):
        """Allocate per-row physical pages for len+n_new tokens. Logical
        pages a row does not own map to one shared TRASH page, so
        prefill's right-pad writes land harmlessly (they are never
        attended: the mask stops at each row's frontier).

        The physical pool size P is BUCKETED to a power of two exactly
        like S: jax.jit keys compiled programs on the pool shape, so an
        exact ``sum(need)+1`` pool would recompile prefill AND the fused
        decode scan on nearly every distinct batch length-mix. On the
        bucket lattice, every mix whose page demand lands in the same
        bucket reuses the same compiled programs (the extra pages are
        never referenced by any table entry below the trash id)."""
        cfg = self._model.config
        B = len(lengths)
        npages = -(-M // page)
        need = [-(-(int(l) + n_new) // page) for l in lengths]
        P = _bucket(sum(need) + 1, lo=8)      # +1 trash page (id P-1)
        trash = P - 1
        table = np.full((B, npages), trash, np.int32)
        nxt = 0
        for b, nb in enumerate(need):
            table[b, :nb] = np.arange(nxt, nxt + nb)
            nxt += nb
        shape = (P, cfg.num_kv_heads, page, cfg.head_dim)
        # one table copy per layer: the cache pytree is DONATED to the
        # compiled step, and XLA rejects donating one buffer twice
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                 jnp.asarray(table))
                for _ in range(cfg.num_layers)], P

    def generate(self, input_ids, max_new_tokens: Optional[int] = None,
                 lengths=None, **overrides):
        """Batched generation; one compiled prefill + ONE compiled
        decode program for the whole token loop. ``lengths`` gives the
        true per-row prompt lengths for right-padded ragged batches;
        ragged rows decode at per-row offsets (own rope positions,
        cache slots, and attention frontier), stopping per row at
        ``eos_token_id`` when set (later slots filled with eos)."""
        gen = GenerationConfig(**{
            **self.config.generation.__dict__,
            **({"max_new_tokens": max_new_tokens}
               if max_new_tokens is not None else {}),
            **overrides})
        ids = np.asarray(input_ids._value if isinstance(input_ids, Tensor)
                         else input_ids)
        B, S0 = ids.shape
        if lengths is None:
            lengths = np.full((B,), S0, np.int32)
        lengths = np.asarray(lengths, np.int32)
        n_new = gen.max_new_tokens
        M = self._max_len(S0, n_new)
        # bucket never past the cache: a 90-token prompt with
        # max_length=100 must prefill at Sb=100, not bucket 128
        Sb = min(_bucket(S0), M)
        ragged = int(lengths.min()) != int(lengths.max())
        from ..core.enforce import enforce

        enforce(int(lengths.max()) + n_new <= M,
                f"prompt ({int(lengths.max())}) + max_new_tokens ({n_new}) "
                f"exceeds cache length {M}; raise config.max_length")
        model = self._model
        p_dtype = self._params[0]._value.dtype
        pvals = tuple(p._value for p in self._params)
        page = self.config._kv_page_size
        if page:
            caches, P = self._paged_caches(lengths, n_new, M, page,
                                           p_dtype)
        else:
            caches = model._empty_caches(B, M, p_dtype)
            P = 0

        ids_p = np.zeros((B, Sb), ids.dtype)
        ids_p[:, :S0] = ids
        # B is the caller's batch by contract (one program per batch
        # size); the ServingEngine pins B for traffic-grade serving
        # tpulint: disable=recompile-hazard
        prefill = self._prefill_fn(B, Sb, M)
        self.stats.note("prefill", (B, Sb, M, page, P, str(ids_p.dtype),
                                    str(p_dtype)))
        last, caches = prefill(pvals, jnp.asarray(ids_p), caches,
                               jnp.asarray(lengths))

        rng = jax.random.PRNGKey(gen.seed)
        rng, sub = jax.random.split(rng)
        # first sampled token (same rule as the compiled loop)
        # B: static per-call batch, same contract as prefill above
        # tpulint: disable=recompile-hazard
        decode = self._decode_fn(B, M, n_new - 1, gen, ragged,
                                 bool(page)) if n_new > 1 else None
        if decode is not None:
            self.stats.note("decode", (B, M, n_new - 1, gen.temperature,
                                       gen.top_k, gen.top_p,
                                       gen.eos_token_id, ragged, page, P,
                                       str(p_dtype)))
        self.stats.count_tokens(("generate", B, Sb, P), B * n_new)
        tok0 = _sample(last, sub, gen)
        # ragged rows decode at PER-ROW offsets: each row's rope
        # positions, cache-write slot, and attention frontier advance
        # from its own true length (no lockstep from max(lengths))
        pos0 = jnp.asarray(lengths) if ragged else int(lengths.max())
        if decode is not None:
            toks, caches = decode(pvals, tok0, caches, pos0, rng)
            all_new = jnp.concatenate([tok0[:, None], toks], axis=1)
        else:
            all_new = tok0[:, None]
        out = jnp.concatenate([jnp.asarray(ids), all_new], axis=1)
        return Tensor(out, stop_gradient=True)


from .serving import ServingEngine, ServingRequest  # noqa: E402
from .disagg import KVMigrator, MigrationCorruptError  # noqa: E402
from .router import Replica, Router, RouterServer  # noqa: E402
