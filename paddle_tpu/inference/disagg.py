"""Disaggregated prefill/decode serving: live KV page migration.

Long prompts are compute-bound and decode is HBM-bound (the Gemma-on-
TPU serving roofline split in PAPERS.md), so co-locating both phases on
one replica always leaves one resource idle. This module splits a
serving fleet by phase: ``phase="prefill"`` replicas run the chunked
``[B, Sc]`` unified step at full MFU and park each request the moment
its first token samples; a :class:`KVMigrator` then streams the
request's committed KV pages to a ``phase="decode"`` replica running
the cheap fused decode scan at high batch. The Ragged Paged Attention
paper's location-independent page indirection is what makes the pages
movable at all — a migrated page is just a pool row plus a block-table
entry on the receiving side.

Wire format (``pack_migration`` / ``unpack_migration``): one
``[2*layers, kv_heads, page, head_dim]`` payload array per committed
page, each crc32-checked with the SAME shard codec the checkpoint
writer/loader uses (``distributed.checkpoint.array_crc32``), plus the
row's block table and the host request state (prompt, committed
tokens, trace identity). A crc mismatch raises
:class:`MigrationCorruptError` and the request is retried on a fresh
replica — exact, because a greedy prefill restart recommits the same
first token.

Byte accounting is ledger-exact at a closed form per request::

    wire_bytes = committed_pages * page_bytes + block_table_row_bytes

Every migration books its payload on the comm ledger
(observability/commledger) as point-to-point ``ppermute`` records
under the ``migrate`` axis — ``wire_bytes("ppermute", payload) ==
payload`` — so ``paddle_tpu_comm_bytes_total{axis="migrate"}`` and
``paddle_tpu_serving_migration_bytes_total`` pin to the closed form
exactly.

Backpressure: a decode replica refuses a migration (``can_import``
False — no free slot or pages) and the row simply stays parked on its
prefill replica, holding its pages. A page-starved prefill replica
then stalls admissions and, when nothing else can move, bounces its
youngest mid-prefill row back to the queue head (PR 12's preemption) —
no token has been sampled for that row, so the restart is exact.

Compile stability: export reads pages through the engine's ONE
compiled page-read program (traced src index) and import writes them
through the ONE page-write program (traced dst index), so a warmed
fleet migrates with ZERO additional XLA compiles on either replica
kind.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..distributed.checkpoint import array_crc32
from ..observability import commledger as _cl
from ..observability.catalog import serving_metrics as _serving_metrics

__all__ = ["KVMigrator", "MigrationCorruptError", "pack_migration",
           "unpack_migration", "migration_nbytes", "MIGRATE_AXES"]

# the comm-ledger axis migrations are booked under (point-to-point
# page moves between replicas — ppermute semantics: wire == payload)
MIGRATE_AXES = ("migrate",)


class MigrationCorruptError(RuntimeError):
    """A transferred KV page payload failed its crc32 — the migration
    is dropped and the request retried on a fresh replica."""


def migration_nbytes(pkg: Dict[str, Any]) -> int:
    """The closed form for one request's migration wire bytes:
    committed pages x page_bytes + the block-table row."""
    return int(sum(int(a.nbytes) for a in pkg["pages"])
               + int(pkg["table_row"].nbytes))


def pack_migration(pkg: Dict[str, Any]) -> Dict[str, Any]:
    """Frame an exported request for the wire: contiguous page
    payloads with one crc32 each (the checkpoint shard codec) plus
    the closed-form byte count."""
    pages = [np.ascontiguousarray(a) for a in pkg["pages"]]
    table = np.ascontiguousarray(pkg["table_row"])
    wire = dict(pkg)
    wire["pages"] = pages
    wire["table_row"] = table
    wire["page_crc32"] = [array_crc32(a) for a in pages]
    wire["wire_bytes"] = int(sum(a.nbytes for a in pages)
                             + table.nbytes)
    return wire


def unpack_migration(wire: Dict[str, Any]) -> Dict[str, Any]:
    """Verify every page payload against its recorded crc32 (exactly
    like a checkpoint shard on load); raises
    :class:`MigrationCorruptError` on the first mismatch."""
    for j, (a, want) in enumerate(zip(wire["pages"],
                                      wire["page_crc32"])):
        got = array_crc32(a)
        if got != want:
            raise MigrationCorruptError(
                f"KV page payload {j} failed its crc32 ({got:#010x} "
                f"!= recorded {want:#010x}) — dropping the migration "
                "so the request can be retried on a fresh replica")
    return wire


def _retry_info(pkg: Dict[str, Any]) -> Dict[str, Any]:
    """What a router needs to resubmit a failed migration's request
    from scratch (greedy prefill restart is exact)."""
    return {"prompt": pkg["prompt"],
            "max_new_tokens": pkg["max_new_tokens"],
            "eos_token_id": pkg["eos_token_id"],
            "trace_id": pkg["trace_id"],
            "parent_span_id": pkg["parent_span_id"]}


class KVMigrator:
    """Streams committed KV pages from prefill replicas to decode
    replicas. ``pump(prefill_replicas)`` is one migration tick: every
    migratable row either moves to an accepting decode replica or
    stays parked (backpressure). Returns one event dict per attempted
    migration: ``{"status": "ok", "src", "src_rid", "dst",
    "dst_rid"}``, or ``{"status": "crc_error" | "refused", "src",
    "src_rid", "request": <resubmit info>}``."""

    def __init__(self, decode_replicas: List[Any]):
        self.decode = list(decode_replicas)
        self._metrics = _serving_metrics()
        # cumulative wire bytes, pinned to the per-request closed form
        self.wire_bytes = 0
        self.migrated = 0

    def _pick(self, prompt_len: int, max_new_tokens: int):
        """The least-loaded decode replica that can adopt this
        geometry right now, or None (backpressure)."""
        cands = [e for e in self.decode
                 if e.can_import(prompt_len, max_new_tokens)]
        if not cands:
            return None
        return max(cands, key=lambda e: e._avail_pages())

    def _transmit(self, wire: Dict[str, Any]) -> Dict[str, Any]:
        """The wire seam: in-process fleets hand the frame over
        directly; a cross-host transport (or a fault-injecting test)
        overrides this."""
        return wire

    def pump(self, prefill_replicas: List[Any]) -> List[Dict[str, Any]]:
        """One migration tick over the prefill side of the fleet."""
        events = []
        for peng in prefill_replicas:
            for rid in list(peng.migratable()):
                s = next(s for s in peng.slots
                         if s is not None and s.req.rid == rid)
                dst = self._pick(len(s.req.prompt),
                                 s.req.max_new_tokens)
                if dst is None:
                    # row stays parked holding its pages; the prefill
                    # replica's own stall/preempt machinery throttles
                    self._metrics["migrations"].inc(result="refused")
                    continue
                events.append(self._migrate(peng, rid, dst))
        return events

    def _migrate(self, src, rid: int, dst) -> Dict[str, Any]:
        t0 = time.perf_counter()
        pkg = src.export_request(rid)
        wire = self._transmit(pack_migration(pkg))
        nbytes = int(wire["wire_bytes"])
        # ledger-exact booking: every byte on the migration wire is a
        # point-to-point page move, recorded like any collective —
        # ppermute wire == payload, so the ledger total IS the closed
        # form pages x page_bytes + block-table row
        with _cl.capture() as led:
            for arr in wire["pages"]:
                _cl.note("ppermute", MIGRATE_AXES, arr.shape,
                         arr.dtype, p=2)
            _cl.note("ppermute", MIGRATE_AXES, wire["table_row"].shape,
                     wire["table_row"].dtype, p=2)
        led.publish(self._metrics["comm_bytes"],
                    self._metrics["comm_ops"])
        self.wire_bytes += nbytes
        self._metrics["migration_bytes"].inc(nbytes)
        try:
            pkg2 = unpack_migration(wire)
        except MigrationCorruptError as e:
            self._metrics["migrations"].inc(result="crc_error")
            return {"status": "crc_error", "src": src, "src_rid": rid,
                    "error": str(e), "request": _retry_info(pkg)}
        nrid = dst.import_request(pkg2)
        if nrid is None:
            # the capacity check raced an admission on the decode
            # replica; the export already evicted the row, so the
            # request restarts from scratch like a corrupt frame
            self._metrics["migrations"].inc(result="refused")
            return {"status": "refused", "src": src, "src_rid": rid,
                    "request": _retry_info(pkg)}
        self.migrated += 1
        self._metrics["migrations"].inc(result="ok")
        self._metrics["migration_seconds"].observe(
            time.perf_counter() - t0)
        return {"status": "ok", "src": src, "src_rid": rid,
                "dst": dst, "dst_rid": nrid}
