"""Continuous-batching serving engine over the ragged paged KV cache.

The ``Predictor`` serves one batch per ``generate()`` call: every row
starts and finishes together (static batching), and the physical page
pool is sized per call. This module adds the traffic-grade layer the
reference serves with block_multi_head_attention + its serving runtime
(reference capability: llm.predictor / fused blha continuous batching;
design per the Ragged Paged Attention paper in PAPERS.md — ONE compiled
program for arbitrary length mixes):

- ``ServingEngine`` owns ONE fixed-size physical page pool (its shape
  never changes for the engine's lifetime) plus a host-side page free
  list. Requests are admitted into B slots of an in-flight batch; a
  request's pages are popped from the free list at admission and pushed
  back at completion — eviction + backfill, not drain-and-refill.
- PREFILL runs per arrival at [1, Sb] with Sb on the same power-of-two
  bucket lattice as the Predictor, writing straight into the arrival's
  pages through its block-table row (right-pad writes land in the
  shared trash page).
- DECODE is one shared compiled step for the whole batch: [B, 1] tokens
  at per-row offsets against the shared pool. Free slots ride along
  with an all-trash table row (their writes land in the trash page,
  their outputs are ignored) so the program shape is ALWAYS
  (B, pool_bucket) — admissions and evictions never change a compiled
  shape. ``decode_chunk`` fuses that many decode steps into one
  ``lax.scan`` launch; admission/eviction happens at chunk boundaries.

CHUNKED PREFILL (``prefill_chunk``, the Ragged Paged Attention design):
the per-arrival prefill program above head-of-line-blocks every decode
row for the length of the longest arriving prompt. With a chunk size
set, prompts are instead split into <= Sc-token chunks (Sc power-of-two
bucketed, a multiple of the page size) and folded into ONE unified
compiled step of fixed shape [B, Sc]: every row carries host-side
``(kind, start, seq_len)`` metadata — a prefill row feeds its next
chunk (seq_len <= Sc), a decode row its last sampled token
(seq_len = 1), an idle row nothing (seq_len = 0) — and the attention
inside is the ragged paged kernel (ops/pallas/ragged_paged_attention),
whose per-row DMA frontier makes the one program's HBM traffic come in
at or below the old two-program sum. A token-budget policy
(``prefill_token_budget``) caps prefill tokens per step so decode rows
always advance: the worst-case inter-token stall under a long-prompt
arrival drops from one full prefill to one chunk round. Rounds with no
chunk to feed fall back to the cheap fused [B, 1] decode scan — both
programs live on the same fixed lattice, so the zero-recompile
guarantee is unchanged. Pages are reserved INCREMENTALLY per chunk
(admission needs only the first chunk's pages; the decode tail is
reserved before the last chunk feeds), so a long prompt no longer
hoards pages it cannot use yet; a page-starved engine preempts the
youngest mid-prefill row (no tokens sampled yet — restart is exact)
back to the queue head rather than deadlock.

PREFIX CACHE (``prefix_cache=True``, chunked mode): chunk frontiers
land exactly on page boundaries (Sc is a multiple of the page size),
so a completed page holds the KV of one page-aligned prompt chunk and
nothing else. Physical pages become ref-counted and content-addressed:
a rolling hash per page-aligned prompt chunk keys completed pages, and
admission maps the longest cached prefix straight into the new slot's
block table (refcount++, ZERO copies, zero FLOPs — the Ragged Paged
Attention indirection makes a shared page addressable from any row).
``_plan_chunks`` then starts prefill at the first cold chunk, so
fleets sharing a system prompt skip its prefill entirely. Registered
pages are IMMUTABLE; page-aligned frontiers mean the only write that
can ever land in a hit page is the full-prompt-hit refeed of the last
prompt token, which copy-on-writes that one page first (one compiled
dynamic-slice copy program, traced src/dst — no recompiles). Release
paths (_finish / _preempt_youngest) decrement refcounts; idle cached
pages park on an LRU the allocator reclaims under pool pressure, so
the cache yields memory before anything stalls.

SPECULATIVE DECODING (``draft_predictor`` + ``spec_tokens=k``, greedy
chunked mode): a small draft model proposes k greedy tokens per decode
row (one fused [B, 1]-step scan against draft KV pools that SHARE the
engine's page tables and allocator — prefix hits and CoW cover the
draft for free), and ONE verify dispatch on the existing unified
[B, Sc] lattice scores all k+1 positions per row (argmax at every
slot instead of the last — same shapes, zero new program geometries).
The host accepts the longest proposal prefix that matches the
target's own greedy argmax chain and commits accepted+1 tokens per
round, so decode needs ~1/(accepted+1) of the device rounds while the
committed ids stay BIT-IDENTICAL to plain greedy decode (each
committed token equals the target argmax given exactly the committed
history — acceptance only reorders when positions are scored, never
what they are conditioned on).

Compile stability: every program is keyed on the small fixed lattice
(batch B, seq bucket Sb, pool bucket P). After one warmup mix, a stream
with arbitrary length mixes triggers ZERO additional XLA compiles —
asserted via the shared ``CompileStats`` counters (``engine.stats``),
and statically by ``tools/tpulint`` (host-sync-in-jit +
recompile-hazard): every int reaching a ``*_fn`` factory here is either
``_bucket``-quantized (Sb, P) or an engine-lifetime constant (B, M,
chunk, k), and the host syncs (first-token sample, chunk readback,
accept loop) sit outside the compiled scan.
"""
from __future__ import annotations

import threading
import time
from collections import Counter, OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce
from ..observability import commledger as _cl
from ..observability import memledger as _ml
from ..observability.catalog import serving_metrics as _serving_metrics
from ..observability.spans import (RequestTrace, SpanRing,
                                   format_traceparent as
                                   _format_traceparent,
                                   parse_traceparent as
                                   _parse_traceparent)
from ..tensor import Tensor

__all__ = ["ServingEngine", "ServingRequest"]


@dataclass
class ServingRequest:
    """One serving request and (once finished) its result."""

    rid: int
    prompt: np.ndarray                   # [L] int prompt tokens
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    new_tokens: List[int] = field(default_factory=list)
    # telemetry timestamps (perf_counter domain): TTFT = t_first_token
    # - t_submit; TPOT = (t_finish - t_first_token) / (n_tokens - 1)
    t_submit: float = 0.0
    t_first_token: float = 0.0
    t_finish: float = 0.0
    # graceful degradation: why this request was load-shed (None =
    # served normally); admission deadline in the t_submit clock domain
    shed_reason: Optional[str] = None
    deadline: Optional[float] = None
    # W3C trace identity (observability/spans.py): trace_id spans
    # processes, span_id is this request's root span in THIS engine,
    # parent_span_id the submitting caller's span elsewhere
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_span_id: Optional[str] = None

    @property
    def shed(self) -> bool:
        return self.shed_reason is not None

    @property
    def traceparent(self) -> Optional[str]:
        """The ``00-<trace_id>-<span_id>-01`` header downstream work
        on this request should carry (None before submit stamps the
        identity)."""
        if self.trace_id is None or self.span_id is None:
            return None
        return _format_traceparent(self.trace_id, self.span_id)

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens (the Predictor.generate layout)."""
        return np.concatenate([np.asarray(self.prompt, np.int64),
                               np.asarray(self.new_tokens, np.int64)])


class _Slot:
    """Host-side state of one in-flight batch row."""

    __slots__ = ("req", "pages", "pos", "state", "fed", "chunks", "seq",
                 "hashes", "registered", "hit_pages")

    def __init__(self, req: ServingRequest, pages: List[int],
                 state: str = "decode", seq: int = 0):
        self.req = req
        self.pages = pages
        # cache position the NEXT decode input token is written at
        self.pos = len(req.prompt)
        # chunked-prefill scheduler state: "prefill" while prompt
        # tokens remain unfed, then "decode"; legacy (unchunked) slots
        # are born "decode" because admission prefills synchronously
        self.state = state
        self.fed = 0            # prompt tokens already written
        self.chunks = 0         # chunks fed (span/telemetry index)
        self.seq = seq          # admission order (scheduler fairness)
        # prefix-cache bookkeeping: rolling hash per page-aligned
        # prompt chunk (None with the cache off), how many leading
        # pages are already registered/shared, and how many arrived
        # as cache hits at admission
        self.hashes: Optional[List[int]] = None
        self.registered = 0
        self.hit_pages = 0


class ServingEngine:
    """Continuous batching over a Predictor with a paged KV cache.

    >>> pred = create_predictor(Config().set_model(m).enable_paged_kv(64))
    >>> eng = ServingEngine(pred, max_batch=8)
    >>> rid = eng.submit(prompt_ids, max_new_tokens=64)
    >>> done = eng.run()          # {rid: ServingRequest}
    >>> done[rid].output_ids

    ``submit`` only queues; ``step()`` runs one admission + decode round
    (the unit a serving loop would tick), ``run()`` drains everything.
    """

    def __init__(self, predictor, max_batch: Optional[int] = None,
                 pool_pages=None, decode_chunk: int = 1,
                 trace_ring: int = 256, mem_ledger: bool = False,
                 max_queue: Optional[int] = None,
                 admission_deadline_s: Optional[float] = None,
                 degraded_window_s: float = 30.0,
                 prefill_chunk: Optional[int] = None,
                 prefill_token_budget: Optional[int] = None,
                 prefix_cache: bool = False,
                 draft_predictor=None, spec_tokens: int = 0,
                 host_spill_pages: int = 0,
                 phase: Optional[str] = None,
                 debug_invariants: bool = False):
        import inspect
        import os

        from . import _bucket

        cfg = predictor.config
        enforce(cfg._kv_page_size,
                "ServingEngine serves over the paged KV cache; call "
                "Config.enable_paged_kv(page_size) before "
                "create_predictor")
        self.pred = predictor
        self.page = int(cfg._kv_page_size)
        mcfg = predictor._model.config
        self.M = int(cfg.max_length or mcfg.max_position_embeddings)
        self.npages = -(-self.M // self.page)
        self.B = int(max_batch or cfg.max_batch_size)
        enforce(self.B >= 1 and decode_chunk >= 1,
                "max_batch and decode_chunk must be >= 1")
        self.chunk = int(decode_chunk)
        # chunked prefill: prompts feed the unified [B, Sc] step in
        # <= Sc-token chunks; Sc lives on the shared power-of-two
        # lattice AND is a multiple of the page size (bucket with
        # lo=page gives both), so chunk frontiers land on page
        # boundaries and the compiled shape never varies
        self.chunked = prefill_chunk is not None
        if self.chunked:
            enforce(int(prefill_chunk) >= 1, "prefill_chunk must be >= 1")
            self.Sc = min(_bucket(int(prefill_chunk), lo=self.page),
                          _bucket(self.M, lo=self.page))
            enforce("valid" in inspect.signature(
                predictor._model.forward).parameters,
                "prefill_chunk needs a model whose forward accepts the "
                "unified ragged metadata kwarg `valid` (see "
                "models/llama.py)")
            self.prefill_budget = int(prefill_token_budget or self.Sc)
            enforce(self.prefill_budget >= 1,
                    "prefill_token_budget must be >= 1")
        else:
            self.Sc = 0
            self.prefill_budget = 0
        # disaggregated serving (inference/disagg.py drives the
        # migration): a "prefill" replica parks each row the moment its
        # first token samples, holding the committed KV pages for
        # export; a "decode" replica only adopts migrated rows (submit
        # is refused). None = unified, both phases on one replica.
        enforce(phase in (None, "prefill", "decode"),
                'ServingEngine phase must be None, "prefill", or '
                '"decode"')
        self.phase = phase
        if phase == "prefill":
            enforce(self.chunked,
                    'phase="prefill" runs the chunked unified step at '
                    "full MFU; set prefill_chunk")
        if phase is not None:
            enforce(draft_predictor is None,
                    "disaggregated phases do not carry the draft "
                    "pools; run speculative decoding on unified "
                    "replicas")
        self._admit_seq = 0
        # chunked-mode admission backpressure: while an active row is
        # page-stalled, new admissions pause so the freed/free pages
        # reach the OLDEST stalled row first (otherwise a preempted
        # request could be readmitted straight into the pages its
        # elder is waiting for — livelock)
        self._page_stalled = False
        self._dtype = predictor._params[0]._value.dtype
        # one pool for the engine's whole lifetime, on the same bucket
        # lattice as Predictor._paged_caches: the compiled programs are
        # keyed on this shape and NEVER change it. pool_pages="auto"
        # sizes it from measured HBM headroom (memledger.
        # suggest_pool_pages: bytes_limit minus the resident params,
        # 10% margin) capped at the geometric maximum the batch can
        # ever reference; backends without memory stats (the CPU
        # harness) fall back to the geometric default.
        geom = self.B * self.npages + 1
        if pool_pages == "auto":
            page_bytes = (2 * mcfg.num_layers * mcfg.num_kv_heads
                          * self.page * mcfg.head_dim
                          * np.dtype(self._dtype).itemsize)
            resident = sum(_ml.shard_bytes(p._value)
                           for p in predictor._params)
            fit = _ml.suggest_pool_pages(jax.devices()[0], page_bytes,
                                         resident)
            want = min(fit, geom) if fit else geom
        else:
            want = pool_pages or geom
        self.P = _bucket(int(want), lo=8)
        self.trash = self.P - 1
        self._free_pages = list(range(self.P - 1))
        # prefix cache: pages become ref-counted and content-
        # addressable. _hash_page maps the rolling prompt-prefix hash
        # of a COMPLETED page-aligned chunk to the physical page that
        # holds its KV; _page_hash is the inverse; _lru keeps
        # registered pages whose refcount dropped to 0 (still
        # hit-able, reclaimed oldest-first under pool pressure). All
        # allocator state moves under ONE re-entrant lock so the
        # accounting stays coherent if a serving loop ever drives the
        # engine from a thread next to the metrics exporter.
        self.prefix = bool(prefix_cache)
        if self.prefix:
            enforce(self.chunked,
                    "prefix_cache needs chunked prefill "
                    "(prefill_chunk): cache hits are whole "
                    "page-aligned chunks the chunk planner skips")
        self._lock = threading.RLock()
        self._refcount = [0] * self.P
        self._hash_page: Dict[int, int] = {}
        self._page_hash: Dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._pfx = {"lookups": 0, "hits": 0, "cow": 0, "reclaimed": 0,
                     "registered": 0, "skipped_tokens": 0,
                     "fed_tokens": 0}
        # host memory tier for the KV cache (distributed/host_offload.py
        # is the training-side twin): up to host_spill_pages reclaimed
        # prefix-cache pages keep their payload in host memory, keyed
        # by the SAME rolling prefix hash, and fault back through the
        # normal admission path (one page allocation + one page write,
        # then registered + idle so the hit run pins it like any cached
        # page). A hash's KV lives device-side OR host-side, never
        # both. Reclaim only STAGES (page, hash) under the lock; the
        # device read that captures the payload runs in _alloc_pages
        # AFTER the lock is released and BEFORE the allocated pages are
        # handed out — the page cannot be rewritten in between, and no
        # jitted dispatch ever runs under self._lock.
        self.spill_pages = int(host_spill_pages or 0)
        enforce(self.spill_pages == 0 or self.prefix,
                "host_spill_pages rides the prefix cache (pages are "
                "keyed by prefix hash); set prefix_cache=True")
        self._spilled: "OrderedDict[int, Any]" = OrderedDict()
        self._spill_pending: List[Tuple[int, int]] = []
        self._spill_ledger: Dict[Tuple[str, str], int] = {}
        self._spill_counts = {"spilled": 0, "faulted": 0, "dropped": 0}
        # debug-mode pool-accounting invariant (free + idle + live
        # partition the pool; refcounts == slot membership) checked
        # after every admit/finish/preempt — the free-list hardening
        # gate for the refcount migration
        self.debug = bool(debug_invariants) or bool(int(os.environ.get(
            "PADDLE_TPU_SERVING_DEBUG", "0") or 0))
        shape = (self.P, mcfg.num_kv_heads, self.page, mcfg.head_dim)
        self.pools = [(jnp.zeros(shape, self._dtype),
                       jnp.zeros(shape, self._dtype))
                      for _ in range(mcfg.num_layers)]
        self.tables = np.full((self.B, self.npages), self.trash, np.int32)
        self.slots: List[Optional[_Slot]] = [None] * self.B
        self.queue: deque = deque()
        self.finished: Dict[int, ServingRequest] = {}
        self.stats = predictor.stats      # shared compile telemetry
        # unified telemetry: TTFT/TPOT histograms, occupancy gauges,
        # admission/eviction/backfill counters (observability/catalog).
        # All host-side — the compiled prefill/decode programs are
        # untouched, so the compile lattice stays exactly as flat
        self._metrics = _serving_metrics()
        self._stats_reported = (self.stats.compiles,
                                self.stats.cache_hits)
        # per-request lifecycle traces (observability/spans): live
        # traces keyed by rid; finished ones land in a bounded ring
        # with Chrome-trace export. Host-side perf_counter floats only.
        self.traces = SpanRing(maxlen=trace_ring)
        self._live_traces: Dict[int, RequestTrace] = {}
        self._round = 0
        # static comm ledgers of the prefill/decode programs (empty on
        # a single-device mesh; populated the first time a program
        # traces with collectives, republished per execution)
        self._ledgers: Dict[Any, Any] = {}
        # per-program HBM memory ledgers (observability/memledger):
        # analyzed at a site's FIRST execution (before the call — the
        # cache buffers are donated) when the knob is on. One extra
        # trace + AOT compile per site; the jit cache and CompileStats
        # are untouched, so the (B, Sb, P) lattice stays exactly flat.
        self._mem_on = bool(mem_ledger) or bool(int(os.environ.get(
            "PADDLE_TPU_MEM_LEDGER", "0") or 0))
        self._mem_ledgers: Dict[Any, Any] = {}
        self._live_peak = 0
        self.gen = cfg.generation
        self._rng = jax.random.PRNGKey(self.gen.seed)
        self._step_fns: Dict[Any, Any] = {}
        self._next_rid = 0
        # speculative decoding: a draft model proposes spec_tokens
        # greedy tokens per decode row; ONE verify dispatch on the
        # SAME unified [B, Sc] lattice scores all k+1 positions per
        # row. The draft's KV pools share the engine's page tables and
        # allocator (same page ids, draft geometry), so prefix hits
        # and copy-on-write cover the draft for free.
        self.spec = int(spec_tokens or 0)
        enforce((draft_predictor is None) == (self.spec == 0),
                "speculative decoding needs BOTH draft_predictor and "
                "spec_tokens >= 1 (or neither)")
        self._draft = draft_predictor
        if draft_predictor is not None:
            enforce(self.chunked,
                    "speculative decoding rides the unified chunked "
                    "step; set prefill_chunk")
            enforce(not self.gen.temperature,
                    "speculative decoding is greedy-verify only "
                    "(temperature=0): acceptance compares the draft "
                    "against the target argmax chain")
            enforce(self.spec >= 1 and self.spec + 1 <= self.Sc,
                    f"spec_tokens must satisfy 1 <= k <= Sc-1 (k+1 "
                    f"verify positions ride one [B, {self.Sc}] row)")
            dcfg = draft_predictor._model.config
            enforce("valid" in inspect.signature(
                draft_predictor._model.forward).parameters,
                "the draft model's forward must accept the unified "
                "ragged metadata kwarg `valid` (see models/llama.py)")
            enforce(dcfg.vocab_size == mcfg.vocab_size,
                    "draft and target models must share a vocabulary")
            enforce(int(dcfg.max_position_embeddings) >= self.M,
                    "draft max_position_embeddings must cover the "
                    "engine's max_length")
            self._draft_dtype = draft_predictor._params[0]._value.dtype
            dshape = (self.P, dcfg.num_kv_heads, self.page,
                      dcfg.head_dim)
            self.draft_pools = [(jnp.zeros(dshape, self._draft_dtype),
                                 jnp.zeros(dshape, self._draft_dtype))
                                for _ in range(dcfg.num_layers)]
        self._spec = {"proposed": 0, "accepted": 0, "rounds": 0,
                      "committed": 0}
        if self.prefix:
            # pre-compile the page-copy program(s) with a trash-page
            # self-copy (a no-op write) so the first real
            # copy-on-write after warmup costs zero compiles
            self._copy_page(self.trash, self.trash)
        # graceful degradation: a bounded admission queue sheds at
        # submit (reason "queue_full"); a per-request admission deadline
        # sheds queued requests whose wait already blew their budget
        # (reason "deadline") BEFORE paying a prefill for them. Shed
        # requests never reach prefill, so TTFT stays honest — the shed
        # path is counted on paddle_tpu_serving_shed_total instead.
        self.max_queue = int(max_queue) if max_queue else None
        self.admission_deadline_s = admission_deadline_s
        self._degraded_window = float(degraded_window_s)
        self._last_shed_time: Optional[float] = None
        # /healthz integration: report "degraded" while shedding
        import weakref

        from ..observability import exporter as _exporter

        ref = weakref.ref(self)

        def _health_provider():
            eng = ref()
            if eng is None:
                return None              # engine gone: exporter prunes
            return {"component": "serving", "status": eng.health()}

        self._health_provider = _health_provider
        _exporter.add_health_provider(_health_provider)

        # durable metrics history: PADDLE_TPU_TIMESERIES_DIR attaches
        # the background registry sampler (observability/timeseries.py;
        # PADDLE_TPU_TIMESERIES_S sets the interval) — host-side only,
        # so serving programs and their compile caches are untouched
        self.sampler = None
        ts_dir = os.environ.get("PADDLE_TPU_TIMESERIES_DIR")
        if ts_dir:
            from ..observability import timeseries as _ts

            try:
                self.sampler = _ts.attach_dir(
                    ts_dir, interval_s=float(os.environ.get(
                        "PADDLE_TPU_TIMESERIES_S", "5.0")))
            except (OSError, ValueError):
                self.sampler = None    # unwritable dir: serve anyway

    # -- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               parent_span_id: Optional[str] = None) -> int:
        """Queue one request; returns its rid (admission happens inside
        step()/run(), when a slot and enough free pages exist).

        Graceful degradation: with ``max_queue`` set, a full queue sheds
        the request immediately (it lands in ``finished`` with
        ``shed_reason="queue_full"`` and zero tokens). ``deadline_s``
        (default: the engine's ``admission_deadline_s``) bounds how long
        the request may wait for admission before being shed.

        Cross-process tracing: ``trace_id`` is either a 32-hex W3C
        trace id or a full ``traceparent`` header (in which case the
        caller's span id is taken from it); ``parent_span_id``
        overrides/supplies the caller's 16-hex span id. Missing pieces
        are generated, so every request ALWAYS carries a valid trace
        identity — read it back from ``ServingRequest.traceparent`` or
        ``trace_context(rid)`` to stitch a multi-replica trace."""
        enforce(self.phase != "decode",
                'a phase="decode" replica only adopts migrated '
                "requests (import_request); route submissions to a "
                "prefill or unified replica")
        ids = np.asarray(prompt._value if isinstance(prompt, Tensor)
                         else prompt).reshape(-1).astype(np.int64)
        n_new = int(max_new_tokens if max_new_tokens is not None
                    else self.gen.max_new_tokens)
        eos = eos_token_id if eos_token_id is not None \
            else self.gen.eos_token_id
        L = len(ids)
        enforce(L >= 1 and n_new >= 1, "empty prompt / max_new_tokens")
        enforce(L + n_new <= self.M,
                f"prompt ({L}) + max_new_tokens ({n_new}) exceeds cache "
                f"length {self.M}; raise Config.max_length")
        enforce(self._pages_needed(L, n_new) <= self.P - 1,
                f"request needs {self._pages_needed(L, n_new)} pages but "
                f"the pool only has {self.P - 1}; raise pool_pages")
        if trace_id is not None and "-" in trace_id:
            # a full traceparent header: the caller's span becomes
            # this trace's parent unless explicitly overridden. A
            # malformed or all-zero header (routers inject these) must
            # not fail the request: mint a fresh trace id and book the
            # reject reason instead.
            try:
                tid, parent = _parse_traceparent(trace_id)
            except ValueError:
                self._metrics["trace_parse_errors"].inc(
                    reason="malformed_traceparent")
                trace_id = None
            else:
                trace_id = tid
                if parent_span_id is None:
                    parent_span_id = parent
        rid = self._next_rid
        self._next_rid += 1
        now = time.perf_counter()
        dls = deadline_s if deadline_s is not None \
            else self.admission_deadline_s
        req = ServingRequest(rid, ids, n_new, eos, t_submit=now,
                             deadline=(now + dls) if dls is not None
                             else None)
        meta = {"prompt_len": L, "max_new_tokens": n_new}
        try:
            tr = RequestTrace(rid, meta=meta, trace_id=trace_id,
                              parent_span_id=parent_span_id)
        except ValueError:
            # bare ids that fail W3C validation get the same
            # treatment: fresh identity, reason on the counter
            self._metrics["trace_parse_errors"].inc(
                reason="invalid_trace_id")
            tr = RequestTrace(rid, meta=meta)
        req.trace_id = tr.trace_id
        req.span_id = tr.span_id
        req.parent_span_id = tr.parent_span_id
        tr.begin("queued", now)
        self._live_traces[rid] = tr
        self._metrics["requests"].inc(event="submitted")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            self._shed(req, "queue_full")
            return rid
        self.queue.append(req)
        self._metrics["queue_depth"].set(len(self.queue))
        return rid

    def _shed(self, req: ServingRequest, reason: str):
        """Load-shed a queued request: it finishes with no tokens, no
        TTFT observation (shed latency must not pollute the latency
        SLO histograms), and a shed counter tick. The decision itself
        lands in the request's span trace as a zero-length "shed"
        event (an "i" instant in the Chrome export), so
        export_request_traces shows shed requests — when and why they
        were turned away — not just the ones that completed."""
        req.shed_reason = reason
        req.t_finish = time.perf_counter()
        self.finished[req.rid] = req
        self._last_shed_time = req.t_finish
        m = self._metrics
        m["shed"].inc(reason=reason)
        tr = self._live_traces.pop(req.rid, None)
        if tr is not None:
            # the queued span closes but is NOT observed on the stage
            # histogram — shed latency stays out of the SLO percentiles
            # exactly like the TTFT exclusion above
            sp = tr.end("queued", req.t_finish)
            tr.meta["shed_reason"] = reason
            tr.add("shed", req.t_finish, req.t_finish,
                   {"reason": reason,
                    "queued_seconds": (sp.seconds if sp is not None
                                       else 0.0)})
            self.traces.add(tr)

    def health(self) -> str:
        """"ok", or "degraded" while the engine is shedding load (a
        shed within ``degraded_window_s``, or the admission queue at
        its bound) — surfaced on /healthz by the metrics exporter."""
        if self.max_queue is not None and \
                len(self.queue) >= self.max_queue:
            return "degraded"
        if self._last_shed_time is not None and \
                time.perf_counter() - self._last_shed_time \
                <= self._degraded_window:
            return "degraded"
        return "ok"

    def _pages_needed(self, L: int, n_new: int) -> int:
        return -(-(L + n_new) // self.page)

    def _pages_for(self, tokens: int) -> int:
        return -(-tokens // self.page)

    # -- page accounting (ref-counted pool + prefix cache) ----------------
    def _avail_pages(self) -> int:
        """Pages the allocator can produce right now: the free list
        plus idle registered pages the LRU would yield."""
        with self._lock:
            return len(self._free_pages) + len(self._lru)

    def _alloc_pages(self, n: int) -> List[int]:
        """Pop n pages at refcount 1 — free list first, then reclaim
        idle cached pages oldest-first. Callers check _avail_pages.
        Reclaims staged for host spill are drained here AFTER the lock
        is released and BEFORE the pages are handed out: the payload is
        still intact (nothing writes a page between reclaim and its
        next prefill dispatch) and the device read never holds the
        lock."""
        with self._lock:
            out = []
            for _ in range(n):
                if not self._free_pages:
                    self._cache_reclaim()
                pg = self._free_pages.pop()
                self._refcount[pg] = 1
                out.append(pg)
        if self._spill_pending:
            self._drain_spills()
        return out

    def _cache_reclaim(self):
        """Evict the oldest idle cached page: unregister its hash and
        return it to the free list (the cache yields under pressure).
        With the host tier on, the (page, hash) pair is staged so
        _alloc_pages captures the payload host-side after release."""
        with self._lock:
            enforce(self._lru, "page pool exhausted: allocator asked "
                    "to reclaim with no idle cached pages")
            pg, _ = self._lru.popitem(last=False)
            h = self._page_hash.pop(pg)
            del self._hash_page[h]
            self._pfx["reclaimed"] += 1
            self._metrics["prefix_events"].inc(event="reclaimed")
            if self.spill_pages:
                self._spill_pending.append((pg, h))
            self._free_pages.append(pg)

    def _ref_page(self, pg: int):
        """Take one reference on a cached page (admission hit); an
        idle page leaves the LRU — it is live again."""
        with self._lock:
            self._refcount[pg] += 1
            if self._refcount[pg] == 1:
                self._lru.pop(pg, None)

    def _release_pages(self, pages: List[int]):
        """Drop one reference per page. A registered page that idles
        parks on the LRU tail (still hit-able); an unregistered one
        goes straight back to the free list."""
        with self._lock:
            for pg in pages:
                self._refcount[pg] -= 1
                if self._refcount[pg] > 0:
                    continue
                if pg in self._page_hash:
                    self._lru[pg] = None
                else:
                    self._free_pages.append(pg)

    def _register_page(self, h: int, pg: int):
        """Publish a completed page under its prefix hash. First
        writer wins: a page already registered (or a hash already
        mapped) stays as-is, so the maps remain a bijection."""
        with self._lock:
            if h in self._hash_page or pg in self._page_hash:
                return
            self._hash_page[h] = pg
            self._page_hash[pg] = h
            self._pfx["registered"] += 1
            self._metrics["prefix_events"].inc(event="registered")

    # -- host spill tier (the serving face of distributed/host_offload) --
    def _note_spill(self, direction: str, nbytes: int):
        """Book one ledger entry and republish the offload gauges.
        Cumulative totals as GAUGES (set, not inc) — the same contract
        as the training tier, so the closed-form cross-check reads one
        number per (component, direction)."""
        with self._lock:
            k = ("kv_page", direction)
            self._spill_ledger[k] = self._spill_ledger.get(k, 0) + nbytes
            host = sum(self._payload_nbytes(p)
                       for p in self._spilled.values())
            vals = dict(self._spill_ledger)
            npages = len(self._spilled)
        m = self._metrics
        for (comp, d), v in vals.items():
            m["offload_bytes"].set(v, component=comp, direction=d)
        m["offload_host"].set(host, component="kv_page")
        m["offload_spilled_pages"].set(npages)

    @staticmethod
    def _payload_nbytes(payload) -> int:
        return sum(int(a.nbytes) for pools in payload if pools
                   for kv in pools for a in kv)

    def _page_read_fn(self):
        """ONE compiled page-read program per pool geometry (traced
        src index — the page-copy discipline): returns the page row of
        every pool, to be copied host-side by the caller."""
        key = ("page_read",)
        if key in self._step_fns:
            return self._step_fns[key]

        def read(pools, src):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, src, axis=0,
                                                   keepdims=False),
                pools)

        self._step_fns[key] = jax.jit(read)
        return self._step_fns[key]

    def _page_write_fn(self):
        """ONE compiled page-write program per pool geometry (traced
        dst index, donated pools): the fault-back inverse of
        _page_read_fn."""
        key = ("page_write",)
        if key in self._step_fns:
            return self._step_fns[key]

        def write(pools, rows, dst):
            return jax.tree_util.tree_map(
                lambda a, r: lax.dynamic_update_slice_in_dim(
                    a, r[None], dst, axis=0),
                pools, rows)

        self._step_fns[key] = jax.jit(write, donate_argnums=(0,))
        return self._step_fns[key]

    def _drain_spills(self):
        """Capture staged reclaim payloads host-side (d2h). Runs with
        the lock RELEASED; the staged pages sit on the free list or in
        the caller's fresh allocation, unwritten until the next
        compiled dispatch, so the read is race-free."""
        with self._lock:
            pending, self._spill_pending = self._spill_pending, []
        fn = self._page_read_fn()
        for pg, h in pending:
            src = jnp.asarray(pg, jnp.int32)
            self.stats.note("page_read",
                            ("target", len(self.pools),
                             str(self._dtype)))
            rows = self._run_captured(("page_read",), fn,
                                      self.pools, src)
            target = [tuple(np.asarray(r) for r in kv) for kv in rows]
            draft = None
            if self._draft is not None:
                self.stats.note("page_read",
                                ("draft", len(self.draft_pools),
                                 str(self._draft_dtype)))
                drows = self._run_captured(("page_read_draft",), fn,
                                           self.draft_pools, src)
                draft = [tuple(np.asarray(r) for r in kv)
                         for kv in drows]
            payload = (target, draft)
            with self._lock:
                self._spilled[h] = payload
                self._spill_counts["spilled"] += 1
                dropped = []
                while len(self._spilled) > self.spill_pages:
                    dropped.append(self._spilled.popitem(last=False))
                self._spill_counts["dropped"] += len(dropped)
            self._note_spill("d2h", self._payload_nbytes(payload))

    def _fault_spilled(self, req: ServingRequest):
        """Fault host-spilled prefix pages back onto the device ahead
        of admission: extend the DEVICE hit run with spilled hashes by
        allocating one page each (normal admission accounting — the
        allocation may itself reclaim/spill colder pages), writing the
        payload back, and registering the page idle so _admit_plan
        pins it like any cached hit."""
        if not self.spill_pages or not self._spilled:
            return
        floor = self._pages_for(min(len(req.prompt), self.Sc)) + 1
        for h in self._prefix_hashes(req.prompt):
            with self._lock:
                if h in self._hash_page:
                    continue          # device run keeps extending
                payload = self._spilled.pop(h, None)
            if payload is None:
                return                # run over: neither cached nor spilled
            if self._avail_pages() <= floor:
                with self._lock:      # keep it host-side for next time
                    self._spilled[h] = payload
                    self._spilled.move_to_end(h, last=False)
                return
            [pg] = self._alloc_pages(1)
            target, draft = payload
            dst = jnp.asarray(pg, jnp.int32)
            fn = self._page_write_fn()
            rows = [tuple(jnp.asarray(a) for a in kv) for kv in target]
            self.stats.note("page_write",
                            ("target", len(self.pools),
                             str(self._dtype)))
            self.pools = self._run_captured(("page_write",), fn,
                                            self.pools, rows, dst)
            if self._draft is not None and draft is not None:
                drows = [tuple(jnp.asarray(a) for a in kv)
                         for kv in draft]
                self.stats.note("page_write",
                                ("draft", len(self.draft_pools),
                                 str(self._draft_dtype)))
                self.draft_pools = self._run_captured(
                    ("page_write_draft",), fn, self.draft_pools,
                    drows, dst)
            self._register_page(h, pg)
            self._release_pages([pg])     # idle + registered: hit-able
            with self._lock:
                self._spill_counts["faulted"] += 1
            self._note_spill("h2d", self._payload_nbytes(payload))

    def spill_stats(self) -> Dict[str, Any]:
        """Host-tier counters: pages spilled/faulted/dropped, resident
        host bytes, and the cumulative transfer ledger per direction."""
        with self._lock:
            out = dict(self._spill_counts)
            out["host_pages"] = len(self._spilled)
            out["host_bytes"] = sum(self._payload_nbytes(p)
                                    for p in self._spilled.values())
            out["transfer_bytes"] = {d: v for (_c, d), v
                                     in self._spill_ledger.items()}
            return out

    def _prefix_hashes(self, prompt: np.ndarray) -> List[int]:
        """Rolling hash per FULL page-aligned prompt chunk: h_j covers
        prompt[:(j+1)*page], so equal hashes mean equal whole
        prefixes — a hit run is always a shared prefix, never a
        shared interior."""
        page = self.page
        arr = np.ascontiguousarray(np.asarray(prompt, np.int64))
        out: List[int] = []
        h = hash(("paddle_tpu_prefix", page))
        for j in range(len(arr) // page):
            h = hash((h, arr[j * page:(j + 1) * page].tobytes()))
            out.append(h)
        return out

    def check_invariants(self):
        """Pool-accounting invariant (the free-list hardening gate):
        free list, idle (LRU) pages, and refcounted live pages
        partition the usable pool exactly; every page's refcount
        equals the number of slots holding it; the hash<->page maps
        stay bijective. Raises on any violation — double free, leak,
        or refcount drift."""
        with self._lock:
            bad: List[str] = []
            usable = self.P - 1
            free, lru = list(self._free_pages), list(self._lru)
            fs, ls = set(free), set(lru)
            held = Counter(pg for s in self.slots if s is not None
                           for pg in s.pages)
            live = {pg for pg in range(usable) if self._refcount[pg] > 0}
            if len(fs) != len(free):
                bad.append("duplicate pages on the free list")
            if self.trash in fs | ls | live:
                bad.append("trash page entered circulation")
            if fs & ls or fs & live or ls & live:
                bad.append("free/idle/live page sets overlap")
            if len(free) + len(lru) + len(live) != usable:
                bad.append(f"free({len(free)}) + idle({len(lru)}) + "
                           f"live({len(live)}) != pool({usable})")
            if set(held) != live:
                bad.append("refcounted pages != pages held by slots")
            drift = {pg: (int(c), self._refcount[pg])
                     for pg, c in held.items()
                     if self._refcount[pg] != c}
            if drift:
                bad.append(f"refcount drift (held, rc): {drift}")
            if len(self._hash_page) != len(self._page_hash) or \
                    set(self._page_hash) != set(self._hash_page.values()):
                bad.append("prefix hash maps out of sync")
            if not ls <= set(self._page_hash):
                bad.append("LRU page not registered in the cache")
            if set(self._spilled) & set(self._hash_page):
                bad.append("hash both device-registered and host-"
                           "spilled (the tier owns a hash exclusively)")
            if len(self._spilled) > max(self.spill_pages, 0):
                bad.append(f"host tier over its cap: "
                           f"{len(self._spilled)} > {self.spill_pages}")
            enforce(not bad,
                    "serving pool invariant violated: " + "; ".join(bad))

    def prefix_cache_stats(self) -> Dict[str, Any]:
        """Host-side prefix-cache counters: page lookups/hits at
        admission, prompt tokens skipped vs fed, copy-on-writes, LRU
        reclaims, plus the current registered/idle page counts."""
        with self._lock:
            out = dict(self._pfx)
            out["hit_rate"] = (out["hits"] / out["lookups"]
                               if out["lookups"] else 0.0)
            out["registered_pages"] = len(self._page_hash)
            out["idle_pages"] = len(self._lru)
            return out

    def spec_stats(self) -> Dict[str, Any]:
        """Speculative-decoding counters: drafts proposed/accepted,
        decode rounds, tokens committed — accept_rate and
        tokens_per_step are the two headline ratios."""
        out = dict(self._spec)
        out["accept_rate"] = (out["accepted"] / out["proposed"]
                              if out["proposed"] else 0.0)
        out["tokens_per_step"] = (out["committed"] / out["rounds"]
                                  if out["rounds"] else 0.0)
        return out

    def _admit_plan(self, req: ServingRequest):
        """Admission plan, pure (no allocation): returns (cold pages
        to allocate now, reserve pages the availability check must
        also cover, cache-hit pages, prefix hashes, fed0 = prompt
        tokens the cache already holds). Legacy: the whole len+new
        footprint. Chunked: only the first chunk's pages past the hit
        run — the rest are reserved incrementally (_plan_chunks). A
        FULL-prompt hit refeeds the last prompt token (fed0 = L-1) so
        the unified step still samples token 0; its copy-on-write
        page is the reserve."""
        L = len(req.prompt)
        if not self.chunked:
            return (self._pages_needed(L, req.max_new_tokens), 0,
                    [], None, 0)
        if not self.prefix:
            return (self._pages_for(min(L, self.Sc)), 0, [], None, 0)
        hashes = self._prefix_hashes(req.prompt)
        hits: List[int] = []
        with self._lock:
            for h in hashes:
                pg = self._hash_page.get(h)
                if pg is None:
                    break
                hits.append(pg)
        k = len(hits)
        fed0 = k * self.page
        if fed0 >= L:
            fed0 = L - 1
        cold = self._pages_for(min(L, fed0 + self.Sc)) - k
        reserve = 1 if fed0 < k * self.page else 0
        return max(cold, 0), reserve, hits, hashes, fed0

    def _pvals(self):
        return tuple(p._value for p in self.pred._params)

    def _admit(self):
        """FIFO-admit queued requests into free slots while pages last;
        each admission runs one bucketed prefill into the shared pool.
        Requests whose admission deadline already passed are shed here,
        BEFORE any prefill is spent on them."""
        while self.queue:
            now = time.perf_counter()
            req = self.queue[0]
            if req.deadline is not None and now > req.deadline:
                self.queue.popleft()
                self._shed(req, "deadline")
                self._metrics["queue_depth"].set(len(self.queue))
                continue
            if self.chunked and self._page_stalled and self.num_active:
                return    # backpressure: stalled elders drain first
            free = [b for b in range(self.B) if self.slots[b] is None]
            if not free:
                return
            # host tier: fault spilled prefix pages back first, so the
            # plan below sees them as ordinary idle cached hits
            self._fault_spilled(req)
            cold, reserve, hits, hashes, fed0 = self._admit_plan(req)
            with self._lock:
                # idle hit pages count toward _avail_pages but are
                # about to be pinned by the hit itself — charge them
                idle_hits = sum(1 for pg in hits
                                if self._refcount[pg] == 0)
            if cold + reserve + idle_hits > self._avail_pages():
                return                    # head-of-line waits for evictions
            self.queue.popleft()
            b = free[0]
            # a backfill is an admission that joins rows mid-decode
            # (the continuous-batching event; a cold admit is not one)
            backfill = self.num_active > 0
            for pg in hits:
                self._ref_page(pg)        # pin BEFORE any reclaim can run
            pages = list(hits) + self._alloc_pages(cold)
            self.tables[b, :] = self.trash
            self.tables[b, :len(pages)] = pages
            slot = _Slot(
                req, pages, state="prefill" if self.chunked else "decode",
                seq=self._admit_seq)
            slot.hashes = hashes
            slot.hit_pages = len(hits)
            slot.registered = len(hits)
            slot.fed = fed0
            self.slots[b] = slot
            self._admit_seq += 1
            m = self._metrics
            m["requests"].inc(event="admitted")
            if hashes is not None:
                self._pfx["lookups"] += len(hashes)
                self._pfx["hits"] += len(hits)
                self._pfx["skipped_tokens"] += fed0
                if hits:
                    m["prefix_events"].inc(len(hits), event="hit")
            if self.debug:
                self.check_invariants()
            if backfill:
                m["requests"].inc(event="backfilled")
            m["queue_depth"].set(len(self.queue))
            tr = self._live_traces.get(req.rid)
            if tr is not None:
                sp = tr.end("queued", time.perf_counter())
                tr.meta["backfill"] = bool(backfill)
                if sp is not None:
                    m["stage_seconds"].observe(sp.seconds,
                                               stage="queued")
            if self.chunked:
                # chunks feed inside the unified rounds; the prefill
                # stage span (admit -> first token) opens here
                if tr is not None:
                    tr.begin("prefill", time.perf_counter())
            else:
                self._prefill(b)

    def _prefill(self, b: int):
        from . import _bucket, _sample

        slot = self.slots[b]
        req = slot.req
        t0 = time.perf_counter()
        L = len(req.prompt)
        Sb = min(_bucket(L), self.M)
        ids = np.zeros((1, Sb), np.int32)
        ids[0, :L] = req.prompt
        caches = [(kp, vp, jnp.asarray(self.tables[b:b + 1]))
                  for kp, vp in self.pools]
        fn = self.pred._prefill_fn(1, Sb, self.M)
        self.stats.note("prefill", (1, Sb, self.M, self.page, self.P,
                                    str(ids.dtype), str(self._dtype)))
        last, caches = self._run_captured(
            ("prefill", Sb), fn, self._pvals(), jnp.asarray(ids), caches,
            jnp.asarray([L], jnp.int32))
        self.pools = [(c[0], c[1]) for c in caches]
        self._rng, sub = jax.random.split(self._rng)
        tok0 = int(np.asarray(_sample(last, sub, self.gen))[0])
        req.new_tokens.append(tok0)
        self.stats.count_tokens(("prefill", Sb, self.P), 1)
        now = time.perf_counter()
        req.t_first_token = now
        m = self._metrics
        m["prefill_seconds"].observe(now - t0)
        m["ttft"].observe(now - req.t_submit)
        m["tokens"].inc(1, phase="prefill")
        tr = self._live_traces.get(req.rid)
        if tr is not None:
            tr.add("prefill", t0, now, {"seq_bucket": Sb})
            m["stage_seconds"].observe(now - t0, stage="prefill")
            tr.begin("decode", now)    # closed at eviction
        if len(req.new_tokens) >= req.max_new_tokens or \
                (req.eos_token_id is not None and tok0 == req.eos_token_id):
            self._finish(b)

    # -- decode ----------------------------------------------------------
    def _decode_step_fn(self):
        """One shared compiled decode program for the whole in-flight
        batch: [B] tokens at per-row offsets against the fixed pool,
        ``chunk`` steps fused in one lax.scan. Keyed ONLY on lattice
        constants — admissions/evictions never change its shape."""
        gen = self.gen
        key = (self.B, self.M, self.chunk, gen.temperature, gen.top_k,
               gen.top_p)
        if key in self._step_fns:
            return self._step_fns[key]
        model, params = self.pred._model, self.pred._params
        chunk = self.chunk
        from . import _sample
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def step(pvals, tok0, caches, pos0, rng):
            def body(carry, _):
                tok, caches, pos, rng = carry
                with no_grad(), bind_params(params, pvals):
                    logits, caches = model.forward(
                        Tensor(tok[:, None], stop_gradient=True),
                        caches=caches, offset=pos)
                lv = (logits._value if isinstance(logits, Tensor)
                      else logits)
                rng, sub = jax.random.split(rng)
                nxt = _sample(lv[:, -1], sub, gen)
                return (nxt, caches, pos + 1, rng), nxt

            (_, caches, _, _), toks = lax.scan(
                body, (tok0, caches, pos0, rng), None, length=chunk)
            return jnp.swapaxes(toks, 0, 1), caches     # [B, chunk]

        self._step_fns[key] = jax.jit(step, donate_argnums=(2,))
        return self._step_fns[key]

    # -- unified chunked-prefill + decode step ---------------------------
    def _unified_step_fn(self):
        """THE unified compiled step (chunked mode): fixed [B, Sc] ids
        at per-row ``(start, seq_len)`` metadata against the shared
        pool — prefill-chunk rows, decode rows, and dead rows in one
        dispatch (the ragged paged-attention kernel underneath). Keyed
        ONLY on lattice constants; the metadata is DATA, not shape."""
        gen = self.gen
        key = ("unified", self.B, self.Sc, self.M, gen.temperature,
               gen.top_k, gen.top_p)
        if key in self._step_fns:
            return self._step_fns[key]
        model, params = self.pred._model, self.pred._params
        from . import _sample
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def step(pvals, ids, caches, starts, nvalid, rng):
            with no_grad(), bind_params(params, pvals):
                logits, caches = model.forward(
                    Tensor(ids, stop_gradient=True), caches=caches,
                    offset=starts, valid=nvalid)
            lv = (logits._value if isinstance(logits, Tensor)
                  else logits)
            # each row samples at its LAST valid slot: a decode row's
            # next token, a final prefill chunk's first token; mid-
            # prefill / dead rows sample garbage the host ignores
            idx = jnp.maximum(nvalid - 1, 0)
            last = jnp.take_along_axis(
                lv, idx[:, None, None], axis=1)[:, 0]
            rng, sub = jax.random.split(rng)
            return _sample(last, sub, gen), caches

        self._step_fns[key] = jax.jit(step, donate_argnums=(2,))
        return self._step_fns[key]

    # -- speculative decoding --------------------------------------------
    def _unified_spec_step_fn(self):
        """The spec-mode unified step: IDENTICAL forward on the same
        [B, Sc] lattice, but greedy argmax at EVERY position instead
        of a sample at the last — a decode row feeding its last token
        plus k draft tokens gets all k+1 verify logits from the one
        dispatch. Keyed only on lattice constants."""
        key = ("unified_spec", self.B, self.Sc, self.M)
        if key in self._step_fns:
            return self._step_fns[key]
        model, params = self.pred._model, self.pred._params
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def step(pvals, ids, caches, starts, nvalid):
            with no_grad(), bind_params(params, pvals):
                logits, caches = model.forward(
                    Tensor(ids, stop_gradient=True), caches=caches,
                    offset=starts, valid=nvalid)
            lv = (logits._value if isinstance(logits, Tensor)
                  else logits)
            toks = jnp.argmax(lv.astype(jnp.float32), axis=-1)
            return toks.astype(jnp.int32), caches

        self._step_fns[key] = jax.jit(step, donate_argnums=(2,))
        return self._step_fns[key]

    def _propose_fn(self):
        """The draft proposal program: k greedy [B, 1] draft steps
        fused in one lax.scan against the draft pools (same page
        tables). Rows not proposing ride along at valid 0 — their
        writes land in the trash column."""
        key = ("propose", self.B, self.spec, self.M)
        if key in self._step_fns:
            return self._step_fns[key]
        model, params = self._draft._model, self._draft._params
        k, M = self.spec, self.M
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def propose(pvals, tok0, caches, pos0, nv):
            def body(carry, _):
                tok, caches, pos = carry
                with no_grad(), bind_params(params, pvals):
                    logits, caches = model.forward(
                        Tensor(tok[:, None], stop_gradient=True),
                        caches=caches, offset=pos, valid=nv)
                lv = (logits._value if isinstance(logits, Tensor)
                      else logits)
                nxt = jnp.argmax(lv[:, -1].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                # rows near their token budget draft past their own
                # horizon; clamp keeps positions in the cache (the
                # overdraft lanes are never read — the host caps
                # acceptance at k_use = remaining - 1)
                pos = jnp.minimum(pos + 1, M - 1)
                return (nxt, caches, pos), nxt

            # k+1 steps for k proposals: the extra step writes the
            # k-th proposal's KV, so a fully-accepted run leaves no
            # gap in the draft cache (its emission is discarded)
            (_, caches, _), toks = lax.scan(
                body, (tok0, caches, pos0), None, length=k + 1)
            return jnp.swapaxes(toks, 0, 1), caches      # [B, k+1]

        self._step_fns[key] = jax.jit(propose, donate_argnums=(2,))
        return self._step_fns[key]

    def _draft_chunk_fn(self):
        """Prompt chunks mirrored into the DRAFT pools (same [B, Sc]
        ragged metadata, logits discarded) so the draft proposes from
        full prompt context once a row reaches decode."""
        key = ("draft_chunk", self.B, self.Sc, self.M)
        if key in self._step_fns:
            return self._step_fns[key]
        model, params = self._draft._model, self._draft._params
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def feed(pvals, ids, caches, starts, nvalid):
            with no_grad(), bind_params(params, pvals):
                _logits, caches = model.forward(
                    Tensor(ids, stop_gradient=True), caches=caches,
                    offset=starts, valid=nvalid)
            return caches

        self._step_fns[key] = jax.jit(feed, donate_argnums=(2,))
        return self._step_fns[key]

    def _draft_pvals(self):
        return tuple(p._value for p in self._draft._params)

    def _extended_tables(self) -> np.ndarray:
        """The model's `valid` contract: one extra trailing table
        column that ALWAYS maps to the trash page (dead-slot and
        overdraft writes land there; attention slices it back off)."""
        return np.concatenate(
            [self.tables,
             np.full((self.B, 1), self.trash, np.int32)], axis=1)

    def _propose(self, k_use: Dict[int, int]) -> np.ndarray:
        """Run the draft proposal scan for this round's decode rows;
        returns the [B, k] proposed ids. Also writes the rows' last
        committed token into the draft KV (keeping the draft cache
        exactly one committed token behind the target's)."""
        B = self.B
        tok = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        nv = np.zeros((B,), np.int32)
        for b, ku in k_use.items():
            if ku <= 0:
                continue
            s = self.slots[b]
            tok[b] = s.req.new_tokens[-1]
            pos[b] = s.pos + len(s.req.new_tokens) - 1
            nv[b] = 1
        caches = [(kp, vp, jnp.asarray(self._extended_tables()))
                  for kp, vp in self.draft_pools]
        fn = self._propose_fn()
        self.stats.note("draft_propose",
                        (B, self.spec, self.M, self.page, self.P,
                         str(self._draft_dtype)))
        toks, caches = self._run_captured(
            ("draft_propose",), fn, self._draft_pvals(),
            jnp.asarray(tok), caches, jnp.asarray(pos),
            jnp.asarray(nv))
        self.draft_pools = [(c[0], c[1]) for c in caches]
        return np.asarray(toks)

    def _draft_feed(self, feeders, ids: np.ndarray, starts: np.ndarray):
        """Mirror this round's prompt chunks into the draft pools
        (decode rows masked to valid 0 — their draft KV advances in
        _propose). Cache-hit pages already hold draft KV from the
        request that first fed them: the pools share page ids."""
        B = self.B
        nvf = np.zeros((B,), np.int32)
        for b, n, _last in feeders:
            nvf[b] = n
        caches = [(kp, vp, jnp.asarray(self._extended_tables()))
                  for kp, vp in self.draft_pools]
        fn = self._draft_chunk_fn()
        self.stats.note("draft_chunk",
                        (B, self.Sc, self.M, self.page, self.P,
                         str(self._draft_dtype)))
        caches = self._run_captured(
            ("draft_chunk", self.Sc), fn, self._draft_pvals(),
            jnp.asarray(ids), caches, jnp.asarray(starts),
            jnp.asarray(nvf))
        self.draft_pools = [(c[0], c[1]) for c in caches]

    def _plan_chunks(self):
        """Pick this round's prefill feeders (admission order) under
        the token budget, reserving pages incrementally: a chunk needs
        pages up to its own frontier only, except the LAST chunk, which
        also secures the decode tail (so decode rows never stall on
        pages). Returns (feeders, stalled): feeders as (row, n_tokens,
        is_last); stalled True when some row's reservation could not be
        met this round (it waits for evictions — or preemption when
        nothing else can move)."""
        feeders: List[tuple] = []
        stalled = False
        budget = self.prefill_budget
        rows = sorted((b for b in range(self.B)
                       if self.slots[b] is not None
                       and self.slots[b].state == "prefill"),
                      key=lambda b: self.slots[b].seq)
        for b in rows:
            if budget <= 0:
                break
            s = self.slots[b]
            L = len(s.req.prompt)
            n = min(L - s.fed, self.Sc, budget)
            if n <= 0:
                continue
            last = s.fed + n == L
            want_tokens = (L + s.req.max_new_tokens) if last \
                else (s.fed + n)
            extra = self._pages_for(want_tokens) - len(s.pages)
            # copy-on-write: shared/registered pages are immutable, so
            # any page this chunk writes into that another slot (or
            # the cache) can still see is copied to a private page
            # first. Page-aligned frontiers make this rare: it only
            # fires on the full-prefix-hit refeed (position L-1 lands
            # in the final hit page).
            cow = self._cow_plan(s, n)
            if max(extra, 0) + len(cow) > self._avail_pages():
                stalled = True
                self._metrics["prefill_stall"].inc()
                continue
            if extra > 0:
                newp = self._alloc_pages(extra)
                self.tables[b, len(s.pages):len(s.pages) + extra] = newp
                s.pages.extend(newp)
            for j in cow:
                self._cow_page(b, j)
            feeders.append((b, n, last))
            budget -= n
        self._page_stalled = stalled
        return feeders, stalled

    # -- copy-on-write ---------------------------------------------------
    def _cow_plan(self, s: _Slot, n: int) -> List[int]:
        """Table columns of ``s`` whose pages the next n-token chunk
        writes into while shared (refcount > 1) or registered in the
        prefix cache — those must be copied before the write."""
        if not self.prefix:
            return []
        jlo = s.fed // self.page
        jhi = (s.fed + n - 1) // self.page
        out: List[int] = []
        with self._lock:
            for j in range(jlo, min(jhi, len(s.pages) - 1) + 1):
                pg = s.pages[j]
                if self._refcount[pg] > 1 or pg in self._page_hash:
                    out.append(j)
        return out

    def _cow_page(self, b: int, j: int):
        """Replace table column j of row b with a private copy of its
        page (device-side copy into a freshly allocated page), then
        drop the reference on the shared original."""
        s = self.slots[b]
        old = s.pages[j]
        [new] = self._alloc_pages(1)
        self._copy_page(old, new)
        s.pages[j] = new
        self.tables[b, j] = new
        self._release_pages([old])
        self._pfx["cow"] += 1
        self._metrics["prefix_events"].inc(event="cow")

    def _page_copy_fn(self):
        """ONE compiled page-copy program per pool geometry: src/dst
        page ids are TRACED scalars (dynamic slice in/out), so every
        (src, dst) pair reuses the same executable — a Python-side
        ``.at[dst].set(pool[src])`` would recompile per pair."""
        key = ("page_copy",)
        if key in self._step_fns:
            return self._step_fns[key]

        def copy(pools, src, dst):
            def one(a):
                row = lax.dynamic_index_in_dim(a, src, axis=0,
                                               keepdims=True)
                return lax.dynamic_update_slice_in_dim(a, row, dst,
                                                       axis=0)

            return jax.tree_util.tree_map(one, pools)

        self._step_fns[key] = jax.jit(copy, donate_argnums=(0,))
        return self._step_fns[key]

    def _copy_page(self, src: int, dst: int):
        """Copy one physical page in every pool (and the draft pools
        when speculative decoding is on — they share page ids)."""
        fn = self._page_copy_fn()
        s = jnp.asarray(src, jnp.int32)
        d = jnp.asarray(dst, jnp.int32)
        self.stats.note("page_copy",
                        ("target", len(self.pools), str(self._dtype)))
        self.pools = self._run_captured(("page_copy",), fn,
                                        self.pools, s, d)
        if self._draft is not None:
            self.stats.note("page_copy",
                            ("draft", len(self.draft_pools),
                             str(self._draft_dtype)))
            self.draft_pools = self._run_captured(
                ("page_copy_draft",), fn, self.draft_pools, s, d)

    def _unified_round(self, feeders):
        """One unified dispatch: every feeder writes its next prompt
        chunk, every decode row advances — one token (plain), or up
        to spec_tokens+1 (speculative: last token + k draft proposals
        verified in the same dispatch), dead rows ride along at
        seq_len 0 — ONE compiled program, fixed shape."""
        t0 = time.perf_counter()
        B = self.B
        spec = self._draft is not None
        ids = np.zeros((B, self.Sc), np.int32)
        starts = np.zeros((B,), np.int32)
        nvalid = np.zeros((B,), np.int32)
        feed = {b: (n, last) for b, n, last in feeders}
        decode_rows = []
        k_use: Dict[int, int] = {}
        for b in range(B):
            s = self.slots[b]
            if s is None:
                continue
            if s.state == "decode":
                # spec: draft up to k tokens but never past the row's
                # remaining budget (the last token is never an input,
                # so remaining-1 verify inputs suffice)
                ku = 0
                if spec:
                    ku = max(0, min(self.spec, s.req.max_new_tokens
                                    - len(s.req.new_tokens) - 1))
                k_use[b] = ku
                ids[b, 0] = s.req.new_tokens[-1]
                starts[b] = s.pos + len(s.req.new_tokens) - 1
                nvalid[b] = 1 + ku
                decode_rows.append(b)
            elif b in feed:
                n, _last = feed[b]
                ids[b, :n] = s.req.prompt[s.fed:s.fed + n]
                starts[b] = s.fed
                nvalid[b] = n
            # stalled/out-of-budget prefill rows and free slots stay
            # at seq_len 0: no writes (redirected to the trash
            # column), no attention, output ignored
        if spec and any(k_use[b] > 0 for b in decode_rows):
            drafts = self._propose(k_use)
            for b in decode_rows:
                ku = k_use[b]
                if ku > 0:
                    ids[b, 1:1 + ku] = drafts[b, :ku]
        tbl = self._extended_tables()
        caches = [(kp, vp, jnp.asarray(tbl)) for kp, vp in self.pools]
        if spec:
            fn = self._unified_spec_step_fn()
            self.stats.note("unified_spec",
                            (B, self.Sc, self.M, self.page, self.P,
                             str(self._dtype)))
            toks, caches = self._run_captured(
                ("unified_spec", self.Sc), fn, self._pvals(),
                jnp.asarray(ids), caches, jnp.asarray(starts),
                jnp.asarray(nvalid))
        else:
            fn = self._unified_step_fn()
            self.stats.note("unified",
                            (B, self.Sc, self.M, self.page, self.P,
                             self.gen.temperature, self.gen.top_k,
                             self.gen.top_p, str(self._dtype)))
            self._rng, sub = jax.random.split(self._rng)
            toks, caches = self._run_captured(
                ("unified", self.Sc), fn, self._pvals(),
                jnp.asarray(ids), caches, jnp.asarray(starts),
                jnp.asarray(nvalid), sub)
        self.pools = [(c[0], c[1]) for c in caches]
        # mirror the chunks into the draft pools BEFORE commits can
        # retire a feeder (a finished row's table goes all-trash, and
        # its registered pages must carry draft KV into the cache)
        if spec and feeders:
            self._draft_feed(feeders, ids, starts)
        toks = np.asarray(toks)     # plain: [B]; spec: [B, Sc]
        now = time.perf_counter()
        m = self._metrics
        fed_tokens = 0
        for b, n, last in feeders:
            s = self.slots[b]
            req = s.req
            tr = self._live_traces.get(req.rid)
            if tr is not None:
                # per-chunk span: Chrome request traces show chunk
                # scheduling interleaved with the decode rounds
                tr.add("prefill_chunk", t0, now,
                       {"chunk": s.chunks, "tokens": n, "start": s.fed})
            s.fed += n
            s.chunks += 1
            fed_tokens += n
            m["prefill_chunks"].inc()
            if self.prefix and s.hashes:
                # pages fully behind the fed frontier now hold final
                # immutable KV: publish them under their prefix hash
                full = s.fed // self.page
                for j in range(s.registered, full):
                    self._register_page(s.hashes[j], s.pages[j])
                s.registered = max(s.registered, full)
            if last:
                tok0 = int(toks[b, nvalid[b] - 1]) if spec \
                    else int(toks[b])
                req.new_tokens.append(tok0)
                req.t_first_token = now
                m["ttft"].observe(now - req.t_submit)
                m["tokens"].inc(1, phase="prefill")
                s.state = "decode"
                if tr is not None:
                    sp = tr.end("prefill", now)
                    if sp is not None:
                        m["prefill_seconds"].observe(sp.seconds)
                        m["stage_seconds"].observe(sp.seconds,
                                                   stage="prefill")
                    tr.begin("decode", now)    # closed at eviction
                if len(req.new_tokens) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and tok0 == req.eos_token_id):
                    self._finish(b)
                elif self.phase == "prefill":
                    # disaggregated: the committed KV pages are ready
                    # to stream out — park the row for export
                    # (migratable/export_request) instead of decoding
                    # it on this replica
                    s.state = "migrate"
        if self.prefix:
            self._pfx["fed_tokens"] += fed_tokens
        emitted = 0
        for b in decode_rows:
            req = self.slots[b].req
            acc = 0
            if spec:
                # greedy verify: toks[b, i] is the target argmax AFTER
                # consuming input i, so draft i is accepted iff it
                # equals the previous committed token's argmax —
                # commit the accepted run plus the one bonus token
                ku = k_use[b]
                seq = [int(toks[b, 0])]
                for i in range(1, ku + 1):
                    if int(ids[b, i]) != seq[-1]:
                        break
                    seq.append(int(toks[b, i]))
                acc = len(seq) - 1
                self._spec["proposed"] += ku
                self._spec["accepted"] += acc
            else:
                seq = [int(toks[b])]
            tr = self._live_traces.get(req.rid)
            if tr is not None:
                meta = {"round": self._round, "unified": True}
                if spec:
                    meta.update(proposed=k_use[b], accepted=acc)
                tr.add("decode_round", t0, now, meta)
            for t in seq:
                req.new_tokens.append(t)
                emitted += 1
                if len(req.new_tokens) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and t == req.eos_token_id):
                    self._finish(b)
                    break           # rest of the run is discarded
        if spec and decode_rows:
            # rounds counts decode-ROW verify steps (one per row per
            # dispatch), so committed/rounds is the per-row
            # tokens-per-step ratio: 1.0 at zero acceptance
            self._spec["rounds"] += len(decode_rows)
            self._spec["committed"] += emitted
        self.stats.count_tokens(
            (("unified_spec" if spec else "unified"), self.Sc, self.P),
            fed_tokens + emitted)
        m["unified_round_seconds"].observe(now - t0)
        if emitted:
            m["tokens"].inc(emitted, phase="decode")
        self._round += 1

    def _preempt_youngest(self):
        """Deadlock breaker: when every mid-prefill row is stalled on
        pages and no decode row can free any, bounce the YOUNGEST
        mid-prefill row back to the queue head — it has sampled no
        token yet, so restarting its prefill from scratch is exact.
        The oldest row is never preempted, so it monotonically acquires
        pages and the engine always makes progress."""
        rows = [b for b in range(self.B)
                if self.slots[b] is not None
                and self.slots[b].state == "prefill"]
        if len(rows) <= 1:
            return                  # never preempt the only/oldest row
        b = max(rows, key=lambda b: self.slots[b].seq)
        s = self.slots[b]
        now = time.perf_counter()
        # refcount-aware release: pages shared with elder slots (or
        # registered in the prefix cache) survive the preemption —
        # the sharers keep decoding against them untouched
        self._release_pages(s.pages)
        self.tables[b, :] = self.trash
        self.slots[b] = None
        self.queue.appendleft(s.req)
        m = self._metrics
        m["requests"].inc(event="preempted")
        m["queue_depth"].set(len(self.queue))
        tr = self._live_traces.get(s.req.rid)
        if tr is not None:
            tr.end("prefill", now)     # partial prefill span, kept
            tr.add("preempt", now, now,
                   {"reason": "pages", "fed": s.fed})
            tr.begin("queued", now)
        if self.debug:
            self.check_invariants()

    def _chunked_round(self):
        """One chunked-mode tick: feed chunks through the unified step
        when any are ready (decode rows ride along); otherwise run the
        cheap fused decode scan — except in spec mode, where decode
        rows always take the unified verify path (draft proposals need
        the [B, Sc] lattice); preempt only when nothing can move."""
        feeders, stalled = self._plan_chunks()
        has_decode = any(s is not None and s.state == "decode"
                         for s in self.slots)
        if feeders or (self._draft is not None and has_decode):
            self._unified_round(feeders)
        elif has_decode:
            self._decode_round()
        elif stalled:
            self._preempt_youngest()

    def _decode_round(self):
        active = [b for b in range(self.B) if self.slots[b] is not None
                  and self.slots[b].state == "decode"]
        if not active:
            return
        t0 = time.perf_counter()
        round_traces = [self._live_traces.get(self.slots[b].req.rid)
                        for b in active]
        tok = np.zeros((self.B,), np.int32)
        pos = np.zeros((self.B,), np.int32)
        for b in active:
            s = self.slots[b]
            tok[b] = s.req.new_tokens[-1]
            pos[b] = s.pos + len(s.req.new_tokens) - 1
        # free slots ride along at pos 0 with an all-trash table row:
        # their writes hit the trash page, their outputs are ignored.
        # In chunked mode, stalled mid-prefill rows ride the same way —
        # their REAL table rows are masked to all-trash for this round
        # so the riding write cannot clobber their fed pages
        tbl = self.tables
        if self.chunked:
            mid_prefill = [b for b in range(self.B)
                           if self.slots[b] is not None
                           and self.slots[b].state == "prefill"]
            if mid_prefill:
                tbl = self.tables.copy()
                tbl[mid_prefill, :] = self.trash
        caches = [(kp, vp, jnp.asarray(tbl))
                  for kp, vp in self.pools]
        fn = self._decode_step_fn()
        self.stats.note("serve_decode",
                        (self.B, self.M, self.chunk, self.P,
                         self.gen.temperature, self.gen.top_k,
                         self.gen.top_p, str(self._dtype)))
        self._rng, sub = jax.random.split(self._rng)
        toks, caches = self._run_captured(
            ("decode",), fn, self._pvals(), jnp.asarray(tok), caches,
            jnp.asarray(pos), sub)
        self.pools = [(c[0], c[1]) for c in caches]
        toks = np.asarray(toks)
        emitted = 0
        for b in active:
            req = self.slots[b].req
            for t in toks[b]:
                t = int(t)
                req.new_tokens.append(t)
                emitted += 1
                if len(req.new_tokens) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and t == req.eos_token_id):
                    self._finish(b)
                    break               # rest of the chunk is discarded
        self.stats.count_tokens(("decode", self.B, self.chunk, self.P),
                                emitted)
        m = self._metrics
        now = time.perf_counter()
        m["decode_round_seconds"].observe(now - t0)
        m["tokens"].inc(emitted, phase="decode")
        # per-request decode-round spans: each request in flight this
        # round gets one "decode_round" span on its trace lane (the
        # Chrome export shows the shared rounds lining up across rids);
        # round_traces was captured before evictions could retire them
        for tr in round_traces:
            if tr is not None:
                tr.add("decode_round", t0, now,
                       {"round": self._round, "chunk": self.chunk})
        self._round += 1

    def _finish(self, b: int):
        """Evict a finished row: one reference dropped per page
        (registered pages park on the cache LRU, the rest return to
        the free list), table row to all-trash, slot open for
        backfill."""
        slot = self.slots[b]
        self._release_pages(slot.pages)
        self.tables[b, :] = self.trash
        self.slots[b] = None
        self.finished[slot.req.rid] = slot.req
        req = slot.req
        req.t_finish = time.perf_counter()
        m = self._metrics
        m["requests"].inc(event="evicted")
        if len(req.new_tokens) > 1 and req.t_first_token:
            m["tpot"].observe((req.t_finish - req.t_first_token)
                              / (len(req.new_tokens) - 1))
        tr = self._live_traces.pop(req.rid, None)
        if tr is not None:
            sp = tr.end("decode", req.t_finish)
            if sp is not None:
                m["stage_seconds"].observe(sp.seconds, stage="decode")
            tr.meta["new_tokens"] = len(req.new_tokens)
            tr.add("e2e", req.t_submit, req.t_finish)
            m["stage_seconds"].observe(req.t_finish - req.t_submit,
                                       stage="e2e")
            self.traces.add(tr)
        if self.debug:
            self.check_invariants()

    # -- disaggregated prefill/decode hooks (inference/disagg.py) --------
    def prefix_match(self, hashes: List[int]) -> int:
        """Leading page-aligned prompt chunks whose KV this replica's
        prefix cache already holds — the router's affinity signal
        (computed over the SAME rolling hashes _prefix_hashes
        registers under)."""
        n = 0
        with self._lock:
            for h in hashes:
                if h not in self._hash_page:
                    break
                n += 1
        return n

    def migratable(self) -> List[int]:
        """rids parked for migration on a prefill replica: prompt
        fully prefilled, first token committed, KV pages held for
        export to a decode replica."""
        return [s.req.rid for s in self.slots
                if s is not None and s.state == "migrate"]

    def can_import(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Whether import_request would accept a request of this
        geometry RIGHT NOW (a free slot plus its full page footprint).
        False is the backpressure signal the disagg layer acts on."""
        if any(s is None for s in self.slots):
            return self._pages_needed(prompt_len, max_new_tokens) \
                <= self._avail_pages()
        return False

    def export_request(self, rid: int) -> Dict[str, Any]:
        """Export one migratable row: the committed KV page payloads
        (read through the compiled page-read program — traced src
        index, so exports never recompile), its block-table row, and
        the host request state; the row is then evicted (pages
        released, slot open for backfill). Each page payload is one
        [2*layers, kv_heads, page, head_dim] array (k/v interleaved
        per layer). Delivery framing — crc32 per page, wire-byte
        booking — lives in inference/disagg.py."""
        b = next((i for i, s in enumerate(self.slots)
                  if s is not None and s.state == "migrate"
                  and s.req.rid == rid), None)
        enforce(b is not None,
                f"rid {rid} is not parked for migration")
        s = self.slots[b]
        req = s.req
        k = self._pages_for(len(req.prompt))  # pages with committed KV
        fn = self._page_read_fn()
        payloads: List[np.ndarray] = []
        for j in range(k):
            src = jnp.asarray(s.pages[j], jnp.int32)
            self.stats.note("page_read",
                            ("target", len(self.pools),
                             str(self._dtype)))
            rows = self._run_captured(("page_read",), fn, self.pools,
                                      src)
            payloads.append(np.stack([np.asarray(a)
                                      for kv in rows for a in kv]))
        now = time.perf_counter()
        tr = self._live_traces.pop(rid, None)
        if tr is not None:
            tr.end("decode", now)
            tr.add("migrate_out", now, now, {"pages": k})
            self.traces.add(tr)
        pkg = {"rid": rid, "prompt": req.prompt,
               "max_new_tokens": req.max_new_tokens,
               "eos_token_id": req.eos_token_id,
               "new_tokens": list(req.new_tokens),
               "t_submit": req.t_submit,
               "t_first_token": req.t_first_token,
               "trace_id": req.trace_id, "parent_span_id": req.span_id,
               "pages": payloads, "table_row": self.tables[b].copy()}
        self._release_pages(s.pages)
        self.tables[b, :] = self.trash
        self.slots[b] = None
        self._metrics["requests"].inc(event="migrated_out")
        if self.debug:
            self.check_invariants()
        return pkg

    def import_request(self, pkg: Dict[str, Any]) -> Optional[int]:
        """Adopt a migrated request on a decode replica: allocate its
        full page footprint, write the committed page payloads through
        the compiled page-write program (traced dst index — imports
        never recompile), and park the row mid-decode exactly where
        the prefill replica stopped. Returns the local rid, or None
        when this replica refuses (no free slot / not enough pages) —
        the disagg layer's backpressure signal. crc verification
        happens in inference/disagg.py BEFORE this call."""
        enforce(self.phase != "prefill",
                "a prefill replica cannot adopt migrated rows")
        prompt = np.asarray(pkg["prompt"], np.int64)
        L, n_new = len(prompt), int(pkg["max_new_tokens"])
        free = [b for b in range(self.B) if self.slots[b] is None]
        if not free or self._pages_needed(L, n_new) > \
                self._avail_pages():
            return None
        b = free[0]
        pages = self._alloc_pages(self._pages_needed(L, n_new))
        fn = self._page_write_fn()
        nl = len(self.pools)
        for j, arr in enumerate(pkg["pages"]):
            rows = [(jnp.asarray(arr[2 * l]),
                     jnp.asarray(arr[2 * l + 1])) for l in range(nl)]
            dst = jnp.asarray(pages[j], jnp.int32)
            self.stats.note("page_write",
                            ("target", nl, str(self._dtype)))
            self.pools = self._run_captured(("page_write",), fn,
                                            self.pools, rows, dst)
        self.tables[b, :] = self.trash
        self.tables[b, :len(pages)] = pages
        rid = self._next_rid
        self._next_rid += 1
        req = ServingRequest(rid, prompt, n_new, pkg["eos_token_id"],
                             new_tokens=list(pkg["new_tokens"]),
                             t_submit=pkg["t_submit"],
                             t_first_token=pkg["t_first_token"])
        slot = _Slot(req, pages, state="decode", seq=self._admit_seq)
        slot.fed = L
        self._admit_seq += 1
        self.slots[b] = slot
        tr = RequestTrace(rid, meta={"prompt_len": L,
                                     "max_new_tokens": n_new,
                                     "migrated": True},
                          trace_id=pkg.get("trace_id"),
                          parent_span_id=pkg.get("parent_span_id"))
        req.trace_id = tr.trace_id
        req.span_id = tr.span_id
        req.parent_span_id = tr.parent_span_id
        now = time.perf_counter()
        tr.add("migrate_in", now, now, {"pages": len(pkg["pages"])})
        tr.begin("decode", now)    # closed at eviction
        self._live_traces[rid] = tr
        self._metrics["requests"].inc(event="migrated_in")
        if self.debug:
            self.check_invariants()
        return rid

    # -- driving ---------------------------------------------------------
    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self.slots)

    def step(self):
        """One serving tick: admit arrivals, then one shared round —
        legacy mode prefills each arrival at admission and decodes the
        batch; chunked mode folds pending prompt chunks and decode rows
        into the unified dispatch (_chunked_round)."""
        self._admit()
        if self.chunked:
            self._chunked_round()
        else:
            self._decode_round()
        self._note_tick()

    def _note_tick(self):
        """Per-tick occupancy gauges + compile-counter deltas, then one
        registry snapshot into the stall flight-record ring."""
        m = self._metrics
        m["queue_depth"].set(len(self.queue))
        m["active_slots"].set(self.num_active)
        with self._lock:
            n_free, n_idle = len(self._free_pages), len(self._lru)
            n_reg = len(self._page_hash)
        m["free_pages"].set(n_free)
        usable = self.P - 1              # trash page is never allocable
        # idle cached pages are reclaimable on demand: occupancy
        # reports pages slots actually hold, not cache residue
        m["page_occupancy"].set(
            (usable - n_free - n_idle) / usable if usable else 0.0)
        if self.prefix:
            lk = self._pfx["lookups"]
            m["prefix_hit_rate"].set(
                self._pfx["hits"] / lk if lk else 0.0)
            m["prefix_pages"].set(n_reg - n_idle, state="active")
            m["prefix_pages"].set(n_idle, state="idle")
            # the hash-table size router prefix-affinity steering
            # reads (idle-list length rides prefix_pages{state=idle})
            m["prefix_hash_entries"].set(n_reg)
        if self._draft is not None:
            pr = self._spec["proposed"]
            m["spec_accept_rate"].set(
                self._spec["accepted"] / pr if pr else 0.0)
            rd = self._spec["rounds"]
            m["spec_tokens_per_step"].set(
                self._spec["committed"] / rd if rd else 0.0)
        rc, rh = self._stats_reported
        if self.stats.compiles > rc:
            m["compiles"].inc(self.stats.compiles - rc, site="serving")
        if self.stats.cache_hits > rh:
            m["cache_hits"].inc(self.stats.cache_hits - rh,
                                site="serving")
        self._stats_reported = (self.stats.compiles,
                                self.stats.cache_hits)
        if self._mem_on:
            lb = _ml.live_bytes()
            if lb:
                self._live_peak = max(self._live_peak, lb)
                m["mem_live"].set(lb)
                m["mem_live_peak"].set(self._live_peak)
        from ..observability import get_registry

        get_registry().snapshot()

    def _run_captured(self, site, fn, *args):
        """Run a compiled program under a comm-ledger capture: when the
        call traces (first execution) its static ledger is stored under
        ``site``; every execution republishes the stored ledger to the
        comm_bytes/comm_ops counters. Single-device programs record
        nothing and publish nothing. With the memory ledger on, the
        site's FIRST execution also stores an XLA memory_analysis of
        the same program (lowered BEFORE the call: the cache buffers
        are donated), republished as mem gauges per execution."""
        if self._mem_on and site not in self._mem_ledgers:
            self._mem_ledgers[site] = _ml.analyze(
                fn, args, program="_".join(str(s) for s in site))
        with _cl.capture() as cap:
            out = fn(*args)
        if len(cap):
            self._ledgers[site] = cap
        led = self._ledgers.get(site)
        if led is not None:
            led.publish(self._metrics["comm_bytes"],
                        self._metrics["comm_ops"])
        mled = self._mem_ledgers.get(site)
        if mled is not None:
            mled.publish(self._metrics)
        return out

    def comm_ledger(self, site) -> Optional[Any]:
        """Static comm ledger of a compiled serving program: site is
        ("decode",), ("prefill", seq_bucket), ("unified", chunk_bucket)
        in chunked mode, ("unified_spec", chunk_bucket) /
        ("draft_propose",) / ("draft_chunk", chunk_bucket) with
        speculative decoding, or ("page_copy",) with the prefix
        cache."""
        return self._ledgers.get(site)

    # -- memory accounting (observability/memledger) ---------------------
    def memory_ledger(self, site=("decode",)) -> Optional[Any]:
        """Static HBM memory ledger of a compiled serving program
        (site as in ``comm_ledger``); populated at the site's first
        execution when the engine was built with ``mem_ledger=True``
        (or PADDLE_TPU_MEM_LEDGER=1)."""
        return self._mem_ledgers.get(site)

    def memory_summary(self) -> Dict[str, Any]:
        """The serving memory section bench lines carry: every
        analyzed executable's byte classes plus the measured resident
        state (params + the KV page pool, with the per-page byte cost
        and pool geometry the "auto" sizing uses)."""
        mcfg = self.pred._model.config
        page_bytes = (2 * mcfg.num_layers * mcfg.num_kv_heads
                      * self.page * mcfg.head_dim
                      * np.dtype(self._dtype).itemsize)
        pool_bytes = sum(_ml.shard_bytes(kp) + _ml.shard_bytes(vp)
                         for kp, vp in self.pools)
        return {
            "executables": {led.program: led.to_dict()
                            for led in self._mem_ledgers.values()},
            "state": {
                "params_bytes": sum(_ml.shard_bytes(p._value)
                                    for p in self.pred._params),
                "kv_pool_bytes": pool_bytes,
                "page_bytes": page_bytes,
                "pool_pages": self.P,
                "live_peak_bytes": self._live_peak,
            },
        }

    def roofline_report(self):
        """Roofline verdict of the shared decode round
        (memledger.roofline): FLOPs from the 2N-per-token forward over
        the full B x chunk round, HBM traffic from the decode
        executable's memory ledger, ICI from its comm ledger's wire
        bytes, against the median measured round time. Serving decode
        is expected HBM-bound on chip (the weight-bandwidth roofline
        bench.py's decode lines report against)."""
        cfg = getattr(self.pred._model, "config", None)
        n_params = None
        fn = getattr(cfg, "num_params", None)
        if callable(fn):
            try:
                n_params = int(fn())
            except Exception:
                n_params = None
        if n_params is None:
            n_params = sum(
                int(np.prod(p._value.shape)) for p in self.pred._params)
        n_dev = max(jax.device_count(), 1)
        fl = 2.0 * n_params * self.B * self.chunk / n_dev
        led = self._mem_ledgers.get(("decode",))
        traffic = led.traffic_bytes if led is not None and \
            led.available else 0.0
        comm = self._ledgers.get(("decode",))
        wire = comm.bytes_for() if comm is not None else 0.0
        step_s = self._metrics["decode_round_seconds"].percentile(50)
        return _ml.roofline(
            step_seconds=step_s, flops_per_step=fl,
            hbm_traffic_bytes=traffic, wire_bytes=wire,
            device=jax.devices()[0], program="decode")

    # -- per-request traces ----------------------------------------------
    def request_traces(self) -> List[Dict[str, Any]]:
        """Finished request traces (bounded ring), oldest first — each
        with its queued/prefill/decode_round/decode/e2e spans."""
        return self.traces.to_dicts()

    def export_request_traces(self, path: Optional[str] = None
                              ) -> Dict[str, Any]:
        """Chrome-trace JSON (chrome://tracing / Perfetto) of the
        finished request traces plus any still in flight; writes to
        ``path`` when given and returns the trace dict. Every event's
        args carry the request's ``trace_id``/``span_id`` (and
        ``parent_span_id`` when the caller supplied one), so traces
        exported by different replicas stitch on ``trace_id``."""
        return self.traces.to_chrome_trace(
            path, extra=list(self._live_traces.values()))

    def trace_context(self, rid: int) -> Optional[Dict[str, Any]]:
        """The W3C trace identity of one request — live or finished —
        or None for an unknown rid. ``traceparent`` is the header a
        router propagates to the NEXT hop (it names this request's
        root span as the parent)::

            {"trace_id", "span_id", "parent_span_id", "traceparent"}
        """
        tr = self._live_traces.get(rid)
        if tr is not None:
            return {"trace_id": tr.trace_id, "span_id": tr.span_id,
                    "parent_span_id": tr.parent_span_id,
                    "traceparent": tr.traceparent}
        req = self.finished.get(rid)
        if req is not None and req.trace_id is not None:
            return {"trace_id": req.trace_id, "span_id": req.span_id,
                    "parent_span_id": req.parent_span_id,
                    "traceparent": req.traceparent}
        return None

    def metrics_snapshot(self):
        """Current registry snapshot (TTFT/TPOT histograms, occupancy,
        counters) — the in-process API bench.py emits from."""
        self._note_tick()
        from ..observability import get_registry

        return get_registry().snapshot()

    def run(self, max_steps: Optional[int] = None
            ) -> Dict[int, ServingRequest]:
        """Drain the queue + in-flight batch; returns {rid: request}."""
        steps = 0
        while self.queue or self.num_active:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.finished
