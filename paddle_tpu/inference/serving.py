"""Continuous-batching serving engine over the ragged paged KV cache.

The ``Predictor`` serves one batch per ``generate()`` call: every row
starts and finishes together (static batching), and the physical page
pool is sized per call. This module adds the traffic-grade layer the
reference serves with block_multi_head_attention + its serving runtime
(reference capability: llm.predictor / fused blha continuous batching;
design per the Ragged Paged Attention paper in PAPERS.md — ONE compiled
program for arbitrary length mixes):

- ``ServingEngine`` owns ONE fixed-size physical page pool (its shape
  never changes for the engine's lifetime) plus a host-side page free
  list. Requests are admitted into B slots of an in-flight batch; a
  request's pages are popped from the free list at admission and pushed
  back at completion — eviction + backfill, not drain-and-refill.
- PREFILL runs per arrival at [1, Sb] with Sb on the same power-of-two
  bucket lattice as the Predictor, writing straight into the arrival's
  pages through its block-table row (right-pad writes land in the
  shared trash page).
- DECODE is one shared compiled step for the whole batch: [B, 1] tokens
  at per-row offsets against the shared pool. Free slots ride along
  with an all-trash table row (their writes land in the trash page,
  their outputs are ignored) so the program shape is ALWAYS
  (B, pool_bucket) — admissions and evictions never change a compiled
  shape. ``decode_chunk`` fuses that many decode steps into one
  ``lax.scan`` launch; admission/eviction happens at chunk boundaries.

CHUNKED PREFILL (``prefill_chunk``, the Ragged Paged Attention design):
the per-arrival prefill program above head-of-line-blocks every decode
row for the length of the longest arriving prompt. With a chunk size
set, prompts are instead split into <= Sc-token chunks (Sc power-of-two
bucketed, a multiple of the page size) and folded into ONE unified
compiled step of fixed shape [B, Sc]: every row carries host-side
``(kind, start, seq_len)`` metadata — a prefill row feeds its next
chunk (seq_len <= Sc), a decode row its last sampled token
(seq_len = 1), an idle row nothing (seq_len = 0) — and the attention
inside is the ragged paged kernel (ops/pallas/ragged_paged_attention),
whose per-row DMA frontier makes the one program's HBM traffic come in
at or below the old two-program sum. A token-budget policy
(``prefill_token_budget``) caps prefill tokens per step so decode rows
always advance: the worst-case inter-token stall under a long-prompt
arrival drops from one full prefill to one chunk round. Rounds with no
chunk to feed fall back to the cheap fused [B, 1] decode scan — both
programs live on the same fixed lattice, so the zero-recompile
guarantee is unchanged. Pages are reserved INCREMENTALLY per chunk
(admission needs only the first chunk's pages; the decode tail is
reserved before the last chunk feeds), so a long prompt no longer
hoards pages it cannot use yet; a page-starved engine preempts the
youngest mid-prefill row (no tokens sampled yet — restart is exact)
back to the queue head rather than deadlock.

Compile stability: every program is keyed on the small fixed lattice
(batch B, seq bucket Sb, pool bucket P). After one warmup mix, a stream
with arbitrary length mixes triggers ZERO additional XLA compiles —
asserted via the shared ``CompileStats`` counters (``engine.stats``),
and statically by ``tools/tpulint`` (host-sync-in-jit +
recompile-hazard): every int reaching a ``*_fn`` factory here is either
``_bucket``-quantized (Sb, P) or an engine-lifetime constant (B, M,
chunk), and the host syncs (first-token sample, chunk readback) sit
outside the compiled scan.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce
from ..observability import commledger as _cl
from ..observability import memledger as _ml
from ..observability.catalog import serving_metrics as _serving_metrics
from ..observability.spans import RequestTrace, SpanRing
from ..tensor import Tensor

__all__ = ["ServingEngine", "ServingRequest"]


@dataclass
class ServingRequest:
    """One serving request and (once finished) its result."""

    rid: int
    prompt: np.ndarray                   # [L] int prompt tokens
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    new_tokens: List[int] = field(default_factory=list)
    # telemetry timestamps (perf_counter domain): TTFT = t_first_token
    # - t_submit; TPOT = (t_finish - t_first_token) / (n_tokens - 1)
    t_submit: float = 0.0
    t_first_token: float = 0.0
    t_finish: float = 0.0
    # graceful degradation: why this request was load-shed (None =
    # served normally); admission deadline in the t_submit clock domain
    shed_reason: Optional[str] = None
    deadline: Optional[float] = None

    @property
    def shed(self) -> bool:
        return self.shed_reason is not None

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens (the Predictor.generate layout)."""
        return np.concatenate([np.asarray(self.prompt, np.int64),
                               np.asarray(self.new_tokens, np.int64)])


class _Slot:
    """Host-side state of one in-flight batch row."""

    __slots__ = ("req", "pages", "pos", "state", "fed", "chunks", "seq")

    def __init__(self, req: ServingRequest, pages: List[int],
                 state: str = "decode", seq: int = 0):
        self.req = req
        self.pages = pages
        # cache position the NEXT decode input token is written at
        self.pos = len(req.prompt)
        # chunked-prefill scheduler state: "prefill" while prompt
        # tokens remain unfed, then "decode"; legacy (unchunked) slots
        # are born "decode" because admission prefills synchronously
        self.state = state
        self.fed = 0            # prompt tokens already written
        self.chunks = 0         # chunks fed (span/telemetry index)
        self.seq = seq          # admission order (scheduler fairness)


class ServingEngine:
    """Continuous batching over a Predictor with a paged KV cache.

    >>> pred = create_predictor(Config().set_model(m).enable_paged_kv(64))
    >>> eng = ServingEngine(pred, max_batch=8)
    >>> rid = eng.submit(prompt_ids, max_new_tokens=64)
    >>> done = eng.run()          # {rid: ServingRequest}
    >>> done[rid].output_ids

    ``submit`` only queues; ``step()`` runs one admission + decode round
    (the unit a serving loop would tick), ``run()`` drains everything.
    """

    def __init__(self, predictor, max_batch: Optional[int] = None,
                 pool_pages=None, decode_chunk: int = 1,
                 trace_ring: int = 256, mem_ledger: bool = False,
                 max_queue: Optional[int] = None,
                 admission_deadline_s: Optional[float] = None,
                 degraded_window_s: float = 30.0,
                 prefill_chunk: Optional[int] = None,
                 prefill_token_budget: Optional[int] = None):
        import os

        from . import _bucket

        cfg = predictor.config
        enforce(cfg._kv_page_size,
                "ServingEngine serves over the paged KV cache; call "
                "Config.enable_paged_kv(page_size) before "
                "create_predictor")
        self.pred = predictor
        self.page = int(cfg._kv_page_size)
        mcfg = predictor._model.config
        self.M = int(cfg.max_length or mcfg.max_position_embeddings)
        self.npages = -(-self.M // self.page)
        self.B = int(max_batch or cfg.max_batch_size)
        enforce(self.B >= 1 and decode_chunk >= 1,
                "max_batch and decode_chunk must be >= 1")
        self.chunk = int(decode_chunk)
        # chunked prefill: prompts feed the unified [B, Sc] step in
        # <= Sc-token chunks; Sc lives on the shared power-of-two
        # lattice AND is a multiple of the page size (bucket with
        # lo=page gives both), so chunk frontiers land on page
        # boundaries and the compiled shape never varies
        self.chunked = prefill_chunk is not None
        if self.chunked:
            enforce(int(prefill_chunk) >= 1, "prefill_chunk must be >= 1")
            self.Sc = min(_bucket(int(prefill_chunk), lo=self.page),
                          _bucket(self.M, lo=self.page))
            import inspect

            enforce("valid" in inspect.signature(
                predictor._model.forward).parameters,
                "prefill_chunk needs a model whose forward accepts the "
                "unified ragged metadata kwarg `valid` (see "
                "models/llama.py)")
            self.prefill_budget = int(prefill_token_budget or self.Sc)
            enforce(self.prefill_budget >= 1,
                    "prefill_token_budget must be >= 1")
        else:
            self.Sc = 0
            self.prefill_budget = 0
        self._admit_seq = 0
        # chunked-mode admission backpressure: while an active row is
        # page-stalled, new admissions pause so the freed/free pages
        # reach the OLDEST stalled row first (otherwise a preempted
        # request could be readmitted straight into the pages its
        # elder is waiting for — livelock)
        self._page_stalled = False
        self._dtype = predictor._params[0]._value.dtype
        # one pool for the engine's whole lifetime, on the same bucket
        # lattice as Predictor._paged_caches: the compiled programs are
        # keyed on this shape and NEVER change it. pool_pages="auto"
        # sizes it from measured HBM headroom (memledger.
        # suggest_pool_pages: bytes_limit minus the resident params,
        # 10% margin) capped at the geometric maximum the batch can
        # ever reference; backends without memory stats (the CPU
        # harness) fall back to the geometric default.
        geom = self.B * self.npages + 1
        if pool_pages == "auto":
            page_bytes = (2 * mcfg.num_layers * mcfg.num_kv_heads
                          * self.page * mcfg.head_dim
                          * np.dtype(self._dtype).itemsize)
            resident = sum(_ml.shard_bytes(p._value)
                           for p in predictor._params)
            fit = _ml.suggest_pool_pages(jax.devices()[0], page_bytes,
                                         resident)
            want = min(fit, geom) if fit else geom
        else:
            want = pool_pages or geom
        self.P = _bucket(int(want), lo=8)
        self.trash = self.P - 1
        self._free_pages = list(range(self.P - 1))
        shape = (self.P, mcfg.num_kv_heads, self.page, mcfg.head_dim)
        self.pools = [(jnp.zeros(shape, self._dtype),
                       jnp.zeros(shape, self._dtype))
                      for _ in range(mcfg.num_layers)]
        self.tables = np.full((self.B, self.npages), self.trash, np.int32)
        self.slots: List[Optional[_Slot]] = [None] * self.B
        self.queue: deque = deque()
        self.finished: Dict[int, ServingRequest] = {}
        self.stats = predictor.stats      # shared compile telemetry
        # unified telemetry: TTFT/TPOT histograms, occupancy gauges,
        # admission/eviction/backfill counters (observability/catalog).
        # All host-side — the compiled prefill/decode programs are
        # untouched, so the compile lattice stays exactly as flat
        self._metrics = _serving_metrics()
        self._stats_reported = (self.stats.compiles,
                                self.stats.cache_hits)
        # per-request lifecycle traces (observability/spans): live
        # traces keyed by rid; finished ones land in a bounded ring
        # with Chrome-trace export. Host-side perf_counter floats only.
        self.traces = SpanRing(maxlen=trace_ring)
        self._live_traces: Dict[int, RequestTrace] = {}
        self._round = 0
        # static comm ledgers of the prefill/decode programs (empty on
        # a single-device mesh; populated the first time a program
        # traces with collectives, republished per execution)
        self._ledgers: Dict[Any, Any] = {}
        # per-program HBM memory ledgers (observability/memledger):
        # analyzed at a site's FIRST execution (before the call — the
        # cache buffers are donated) when the knob is on. One extra
        # trace + AOT compile per site; the jit cache and CompileStats
        # are untouched, so the (B, Sb, P) lattice stays exactly flat.
        self._mem_on = bool(mem_ledger) or bool(int(os.environ.get(
            "PADDLE_TPU_MEM_LEDGER", "0") or 0))
        self._mem_ledgers: Dict[Any, Any] = {}
        self._live_peak = 0
        self.gen = cfg.generation
        self._rng = jax.random.PRNGKey(self.gen.seed)
        self._step_fns: Dict[Any, Any] = {}
        self._next_rid = 0
        # graceful degradation: a bounded admission queue sheds at
        # submit (reason "queue_full"); a per-request admission deadline
        # sheds queued requests whose wait already blew their budget
        # (reason "deadline") BEFORE paying a prefill for them. Shed
        # requests never reach prefill, so TTFT stays honest — the shed
        # path is counted on paddle_tpu_serving_shed_total instead.
        self.max_queue = int(max_queue) if max_queue else None
        self.admission_deadline_s = admission_deadline_s
        self._degraded_window = float(degraded_window_s)
        self._last_shed_time: Optional[float] = None
        # /healthz integration: report "degraded" while shedding
        import weakref

        from ..observability import exporter as _exporter

        ref = weakref.ref(self)

        def _health_provider():
            eng = ref()
            if eng is None:
                return None              # engine gone: exporter prunes
            return {"component": "serving", "status": eng.health()}

        self._health_provider = _health_provider
        _exporter.add_health_provider(_health_provider)

    # -- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None) -> int:
        """Queue one request; returns its rid (admission happens inside
        step()/run(), when a slot and enough free pages exist).

        Graceful degradation: with ``max_queue`` set, a full queue sheds
        the request immediately (it lands in ``finished`` with
        ``shed_reason="queue_full"`` and zero tokens). ``deadline_s``
        (default: the engine's ``admission_deadline_s``) bounds how long
        the request may wait for admission before being shed."""
        ids = np.asarray(prompt._value if isinstance(prompt, Tensor)
                         else prompt).reshape(-1).astype(np.int64)
        n_new = int(max_new_tokens if max_new_tokens is not None
                    else self.gen.max_new_tokens)
        eos = eos_token_id if eos_token_id is not None \
            else self.gen.eos_token_id
        L = len(ids)
        enforce(L >= 1 and n_new >= 1, "empty prompt / max_new_tokens")
        enforce(L + n_new <= self.M,
                f"prompt ({L}) + max_new_tokens ({n_new}) exceeds cache "
                f"length {self.M}; raise Config.max_length")
        enforce(self._pages_needed(L, n_new) <= self.P - 1,
                f"request needs {self._pages_needed(L, n_new)} pages but "
                f"the pool only has {self.P - 1}; raise pool_pages")
        rid = self._next_rid
        self._next_rid += 1
        now = time.perf_counter()
        dls = deadline_s if deadline_s is not None \
            else self.admission_deadline_s
        req = ServingRequest(rid, ids, n_new, eos, t_submit=now,
                             deadline=(now + dls) if dls is not None
                             else None)
        tr = RequestTrace(rid, meta={"prompt_len": L,
                                     "max_new_tokens": n_new})
        tr.begin("queued", now)
        self._live_traces[rid] = tr
        self._metrics["requests"].inc(event="submitted")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            self._shed(req, "queue_full")
            return rid
        self.queue.append(req)
        self._metrics["queue_depth"].set(len(self.queue))
        return rid

    def _shed(self, req: ServingRequest, reason: str):
        """Load-shed a queued request: it finishes with no tokens, no
        TTFT observation (shed latency must not pollute the latency
        SLO histograms), and a shed counter tick. The decision itself
        lands in the request's span trace as a zero-length "shed"
        event (an "i" instant in the Chrome export), so
        export_request_traces shows shed requests — when and why they
        were turned away — not just the ones that completed."""
        req.shed_reason = reason
        req.t_finish = time.perf_counter()
        self.finished[req.rid] = req
        self._last_shed_time = req.t_finish
        m = self._metrics
        m["shed"].inc(reason=reason)
        tr = self._live_traces.pop(req.rid, None)
        if tr is not None:
            # the queued span closes but is NOT observed on the stage
            # histogram — shed latency stays out of the SLO percentiles
            # exactly like the TTFT exclusion above
            sp = tr.end("queued", req.t_finish)
            tr.meta["shed_reason"] = reason
            tr.add("shed", req.t_finish, req.t_finish,
                   {"reason": reason,
                    "queued_seconds": (sp.seconds if sp is not None
                                       else 0.0)})
            self.traces.add(tr)

    def health(self) -> str:
        """"ok", or "degraded" while the engine is shedding load (a
        shed within ``degraded_window_s``, or the admission queue at
        its bound) — surfaced on /healthz by the metrics exporter."""
        if self.max_queue is not None and \
                len(self.queue) >= self.max_queue:
            return "degraded"
        if self._last_shed_time is not None and \
                time.perf_counter() - self._last_shed_time \
                <= self._degraded_window:
            return "degraded"
        return "ok"

    def _pages_needed(self, L: int, n_new: int) -> int:
        return -(-(L + n_new) // self.page)

    def _pages_for(self, tokens: int) -> int:
        return -(-tokens // self.page)

    def _admit_need(self, req: ServingRequest) -> int:
        """Pages admission must secure. Legacy: the request's whole
        len+new footprint (held admission→eviction). Chunked: only the
        FIRST chunk's pages — the rest are reserved incrementally as
        chunks feed (_plan_chunks), so a long prompt no longer blocks
        admission of short requests the free list could serve today."""
        if self.chunked:
            return self._pages_for(min(len(req.prompt), self.Sc))
        return self._pages_needed(len(req.prompt), req.max_new_tokens)

    def _pvals(self):
        return tuple(p._value for p in self.pred._params)

    def _admit(self):
        """FIFO-admit queued requests into free slots while pages last;
        each admission runs one bucketed prefill into the shared pool.
        Requests whose admission deadline already passed are shed here,
        BEFORE any prefill is spent on them."""
        while self.queue:
            now = time.perf_counter()
            req = self.queue[0]
            if req.deadline is not None and now > req.deadline:
                self.queue.popleft()
                self._shed(req, "deadline")
                self._metrics["queue_depth"].set(len(self.queue))
                continue
            if self.chunked and self._page_stalled and self.num_active:
                return    # backpressure: stalled elders drain first
            free = [b for b in range(self.B) if self.slots[b] is None]
            if not free:
                return
            need = self._admit_need(req)
            if need > len(self._free_pages):
                return                    # head-of-line waits for evictions
            self.queue.popleft()
            b = free[0]
            # a backfill is an admission that joins rows mid-decode
            # (the continuous-batching event; a cold admit is not one)
            backfill = self.num_active > 0
            pages = [self._free_pages.pop() for _ in range(need)]
            self.tables[b, :] = self.trash
            self.tables[b, :need] = pages
            self.slots[b] = _Slot(
                req, pages, state="prefill" if self.chunked else "decode",
                seq=self._admit_seq)
            self._admit_seq += 1
            m = self._metrics
            m["requests"].inc(event="admitted")
            if backfill:
                m["requests"].inc(event="backfilled")
            m["queue_depth"].set(len(self.queue))
            tr = self._live_traces.get(req.rid)
            if tr is not None:
                sp = tr.end("queued", time.perf_counter())
                tr.meta["backfill"] = bool(backfill)
                if sp is not None:
                    m["stage_seconds"].observe(sp.seconds,
                                               stage="queued")
            if self.chunked:
                # chunks feed inside the unified rounds; the prefill
                # stage span (admit -> first token) opens here
                if tr is not None:
                    tr.begin("prefill", time.perf_counter())
            else:
                self._prefill(b)

    def _prefill(self, b: int):
        from . import _bucket, _sample

        slot = self.slots[b]
        req = slot.req
        t0 = time.perf_counter()
        L = len(req.prompt)
        Sb = min(_bucket(L), self.M)
        ids = np.zeros((1, Sb), np.int32)
        ids[0, :L] = req.prompt
        caches = [(kp, vp, jnp.asarray(self.tables[b:b + 1]))
                  for kp, vp in self.pools]
        fn = self.pred._prefill_fn(1, Sb, self.M)
        self.stats.note("prefill", (1, Sb, self.M, self.page, self.P,
                                    str(ids.dtype), str(self._dtype)))
        last, caches = self._run_captured(
            ("prefill", Sb), fn, self._pvals(), jnp.asarray(ids), caches,
            jnp.asarray([L], jnp.int32))
        self.pools = [(c[0], c[1]) for c in caches]
        self._rng, sub = jax.random.split(self._rng)
        tok0 = int(np.asarray(_sample(last, sub, self.gen))[0])
        req.new_tokens.append(tok0)
        self.stats.count_tokens(("prefill", Sb, self.P), 1)
        now = time.perf_counter()
        req.t_first_token = now
        m = self._metrics
        m["prefill_seconds"].observe(now - t0)
        m["ttft"].observe(now - req.t_submit)
        m["tokens"].inc(1, phase="prefill")
        tr = self._live_traces.get(req.rid)
        if tr is not None:
            tr.add("prefill", t0, now, {"seq_bucket": Sb})
            m["stage_seconds"].observe(now - t0, stage="prefill")
            tr.begin("decode", now)    # closed at eviction
        if len(req.new_tokens) >= req.max_new_tokens or \
                (req.eos_token_id is not None and tok0 == req.eos_token_id):
            self._finish(b)

    # -- decode ----------------------------------------------------------
    def _decode_step_fn(self):
        """One shared compiled decode program for the whole in-flight
        batch: [B] tokens at per-row offsets against the fixed pool,
        ``chunk`` steps fused in one lax.scan. Keyed ONLY on lattice
        constants — admissions/evictions never change its shape."""
        gen = self.gen
        key = (self.B, self.M, self.chunk, gen.temperature, gen.top_k,
               gen.top_p)
        if key in self._step_fns:
            return self._step_fns[key]
        model, params = self.pred._model, self.pred._params
        chunk = self.chunk
        from . import _sample
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def step(pvals, tok0, caches, pos0, rng):
            def body(carry, _):
                tok, caches, pos, rng = carry
                with no_grad(), bind_params(params, pvals):
                    logits, caches = model.forward(
                        Tensor(tok[:, None], stop_gradient=True),
                        caches=caches, offset=pos)
                lv = (logits._value if isinstance(logits, Tensor)
                      else logits)
                rng, sub = jax.random.split(rng)
                nxt = _sample(lv[:, -1], sub, gen)
                return (nxt, caches, pos + 1, rng), nxt

            (_, caches, _, _), toks = lax.scan(
                body, (tok0, caches, pos0, rng), None, length=chunk)
            return jnp.swapaxes(toks, 0, 1), caches     # [B, chunk]

        self._step_fns[key] = jax.jit(step, donate_argnums=(2,))
        return self._step_fns[key]

    # -- unified chunked-prefill + decode step ---------------------------
    def _unified_step_fn(self):
        """THE unified compiled step (chunked mode): fixed [B, Sc] ids
        at per-row ``(start, seq_len)`` metadata against the shared
        pool — prefill-chunk rows, decode rows, and dead rows in one
        dispatch (the ragged paged-attention kernel underneath). Keyed
        ONLY on lattice constants; the metadata is DATA, not shape."""
        gen = self.gen
        key = ("unified", self.B, self.Sc, self.M, gen.temperature,
               gen.top_k, gen.top_p)
        if key in self._step_fns:
            return self._step_fns[key]
        model, params = self.pred._model, self.pred._params
        from . import _sample
        from ..autograd import no_grad
        from ..distributed.engine import bind_params

        def step(pvals, ids, caches, starts, nvalid, rng):
            with no_grad(), bind_params(params, pvals):
                logits, caches = model.forward(
                    Tensor(ids, stop_gradient=True), caches=caches,
                    offset=starts, valid=nvalid)
            lv = (logits._value if isinstance(logits, Tensor)
                  else logits)
            # each row samples at its LAST valid slot: a decode row's
            # next token, a final prefill chunk's first token; mid-
            # prefill / dead rows sample garbage the host ignores
            idx = jnp.maximum(nvalid - 1, 0)
            last = jnp.take_along_axis(
                lv, idx[:, None, None], axis=1)[:, 0]
            rng, sub = jax.random.split(rng)
            return _sample(last, sub, gen), caches

        self._step_fns[key] = jax.jit(step, donate_argnums=(2,))
        return self._step_fns[key]

    def _plan_chunks(self):
        """Pick this round's prefill feeders (admission order) under
        the token budget, reserving pages incrementally: a chunk needs
        pages up to its own frontier only, except the LAST chunk, which
        also secures the decode tail (so decode rows never stall on
        pages). Returns (feeders, stalled): feeders as (row, n_tokens,
        is_last); stalled True when some row's reservation could not be
        met this round (it waits for evictions — or preemption when
        nothing else can move)."""
        feeders: List[tuple] = []
        stalled = False
        budget = self.prefill_budget
        rows = sorted((b for b in range(self.B)
                       if self.slots[b] is not None
                       and self.slots[b].state == "prefill"),
                      key=lambda b: self.slots[b].seq)
        for b in rows:
            if budget <= 0:
                break
            s = self.slots[b]
            L = len(s.req.prompt)
            n = min(L - s.fed, self.Sc, budget)
            if n <= 0:
                continue
            last = s.fed + n == L
            want_tokens = (L + s.req.max_new_tokens) if last \
                else (s.fed + n)
            extra = self._pages_for(want_tokens) - len(s.pages)
            if extra > len(self._free_pages):
                stalled = True
                self._metrics["prefill_stall"].inc()
                continue
            if extra > 0:
                newp = [self._free_pages.pop() for _ in range(extra)]
                self.tables[b, len(s.pages):len(s.pages) + extra] = newp
                s.pages.extend(newp)
            feeders.append((b, n, last))
            budget -= n
        self._page_stalled = stalled
        return feeders, stalled

    def _unified_round(self, feeders):
        """One unified dispatch: every feeder writes its next prompt
        chunk, every decode row advances one token, dead rows ride
        along at seq_len 0 — ONE compiled program, fixed shape."""
        t0 = time.perf_counter()
        B = self.B
        ids = np.zeros((B, self.Sc), np.int32)
        starts = np.zeros((B,), np.int32)
        nvalid = np.zeros((B,), np.int32)
        feed = {b: (n, last) for b, n, last in feeders}
        decode_rows = []
        for b in range(B):
            s = self.slots[b]
            if s is None:
                continue
            if s.state == "decode":
                ids[b, 0] = s.req.new_tokens[-1]
                starts[b] = s.pos + len(s.req.new_tokens) - 1
                nvalid[b] = 1
                decode_rows.append(b)
            elif b in feed:
                n, _last = feed[b]
                ids[b, :n] = s.req.prompt[s.fed:s.fed + n]
                starts[b] = s.fed
                nvalid[b] = n
            # stalled/out-of-budget prefill rows and free slots stay
            # at seq_len 0: no writes (redirected to the trash
            # column), no attention, output ignored
        # the model's `valid` contract: one extra trailing table
        # column that ALWAYS maps to the trash page (dead-slot writes
        # land there; attention slices it back off)
        tbl = np.concatenate(
            [self.tables, np.full((B, 1), self.trash, np.int32)], axis=1)
        caches = [(kp, vp, jnp.asarray(tbl)) for kp, vp in self.pools]
        fn = self._unified_step_fn()
        self.stats.note("unified",
                        (B, self.Sc, self.M, self.page, self.P,
                         self.gen.temperature, self.gen.top_k,
                         self.gen.top_p, str(self._dtype)))
        self._rng, sub = jax.random.split(self._rng)
        toks, caches = self._run_captured(
            ("unified", self.Sc), fn, self._pvals(), jnp.asarray(ids),
            caches, jnp.asarray(starts), jnp.asarray(nvalid), sub)
        self.pools = [(c[0], c[1]) for c in caches]
        toks = np.asarray(toks)
        now = time.perf_counter()
        m = self._metrics
        fed_tokens = 0
        for b, n, last in feeders:
            s = self.slots[b]
            req = s.req
            tr = self._live_traces.get(req.rid)
            if tr is not None:
                # per-chunk span: Chrome request traces show chunk
                # scheduling interleaved with the decode rounds
                tr.add("prefill_chunk", t0, now,
                       {"chunk": s.chunks, "tokens": n, "start": s.fed})
            s.fed += n
            s.chunks += 1
            fed_tokens += n
            m["prefill_chunks"].inc()
            if last:
                tok0 = int(toks[b])
                req.new_tokens.append(tok0)
                req.t_first_token = now
                m["ttft"].observe(now - req.t_submit)
                m["tokens"].inc(1, phase="prefill")
                s.state = "decode"
                if tr is not None:
                    sp = tr.end("prefill", now)
                    if sp is not None:
                        m["prefill_seconds"].observe(sp.seconds)
                        m["stage_seconds"].observe(sp.seconds,
                                                   stage="prefill")
                    tr.begin("decode", now)    # closed at eviction
                if len(req.new_tokens) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and tok0 == req.eos_token_id):
                    self._finish(b)
        emitted = 0
        for b in decode_rows:
            req = self.slots[b].req
            t = int(toks[b])
            tr = self._live_traces.get(req.rid)
            if tr is not None:
                tr.add("decode_round", t0, now,
                       {"round": self._round, "unified": True})
            req.new_tokens.append(t)
            emitted += 1
            if len(req.new_tokens) >= req.max_new_tokens or \
                    (req.eos_token_id is not None
                     and t == req.eos_token_id):
                self._finish(b)
        self.stats.count_tokens(("unified", self.Sc, self.P),
                                fed_tokens + emitted)
        m["unified_round_seconds"].observe(now - t0)
        if emitted:
            m["tokens"].inc(emitted, phase="decode")
        self._round += 1

    def _preempt_youngest(self):
        """Deadlock breaker: when every mid-prefill row is stalled on
        pages and no decode row can free any, bounce the YOUNGEST
        mid-prefill row back to the queue head — it has sampled no
        token yet, so restarting its prefill from scratch is exact.
        The oldest row is never preempted, so it monotonically acquires
        pages and the engine always makes progress."""
        rows = [b for b in range(self.B)
                if self.slots[b] is not None
                and self.slots[b].state == "prefill"]
        if len(rows) <= 1:
            return                  # never preempt the only/oldest row
        b = max(rows, key=lambda b: self.slots[b].seq)
        s = self.slots[b]
        now = time.perf_counter()
        self._free_pages.extend(s.pages)
        self.tables[b, :] = self.trash
        self.slots[b] = None
        self.queue.appendleft(s.req)
        m = self._metrics
        m["requests"].inc(event="preempted")
        m["queue_depth"].set(len(self.queue))
        tr = self._live_traces.get(s.req.rid)
        if tr is not None:
            tr.end("prefill", now)     # partial prefill span, kept
            tr.add("preempt", now, now,
                   {"reason": "pages", "fed": s.fed})
            tr.begin("queued", now)

    def _chunked_round(self):
        """One chunked-mode tick: feed chunks through the unified step
        when any are ready (decode rows ride along); otherwise run the
        cheap fused decode scan; preempt only when nothing can move."""
        feeders, stalled = self._plan_chunks()
        if feeders:
            self._unified_round(feeders)
        elif any(s is not None and s.state == "decode"
                 for s in self.slots):
            self._decode_round()
        elif stalled:
            self._preempt_youngest()

    def _decode_round(self):
        active = [b for b in range(self.B) if self.slots[b] is not None
                  and self.slots[b].state == "decode"]
        if not active:
            return
        t0 = time.perf_counter()
        round_traces = [self._live_traces.get(self.slots[b].req.rid)
                        for b in active]
        tok = np.zeros((self.B,), np.int32)
        pos = np.zeros((self.B,), np.int32)
        for b in active:
            s = self.slots[b]
            tok[b] = s.req.new_tokens[-1]
            pos[b] = s.pos + len(s.req.new_tokens) - 1
        # free slots ride along at pos 0 with an all-trash table row:
        # their writes hit the trash page, their outputs are ignored.
        # In chunked mode, stalled mid-prefill rows ride the same way —
        # their REAL table rows are masked to all-trash for this round
        # so the riding write cannot clobber their fed pages
        tbl = self.tables
        if self.chunked:
            mid_prefill = [b for b in range(self.B)
                           if self.slots[b] is not None
                           and self.slots[b].state == "prefill"]
            if mid_prefill:
                tbl = self.tables.copy()
                tbl[mid_prefill, :] = self.trash
        caches = [(kp, vp, jnp.asarray(tbl))
                  for kp, vp in self.pools]
        fn = self._decode_step_fn()
        self.stats.note("serve_decode",
                        (self.B, self.M, self.chunk, self.P,
                         self.gen.temperature, self.gen.top_k,
                         self.gen.top_p, str(self._dtype)))
        self._rng, sub = jax.random.split(self._rng)
        toks, caches = self._run_captured(
            ("decode",), fn, self._pvals(), jnp.asarray(tok), caches,
            jnp.asarray(pos), sub)
        self.pools = [(c[0], c[1]) for c in caches]
        toks = np.asarray(toks)
        emitted = 0
        for b in active:
            req = self.slots[b].req
            for t in toks[b]:
                t = int(t)
                req.new_tokens.append(t)
                emitted += 1
                if len(req.new_tokens) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and t == req.eos_token_id):
                    self._finish(b)
                    break               # rest of the chunk is discarded
        self.stats.count_tokens(("decode", self.B, self.chunk, self.P),
                                emitted)
        m = self._metrics
        now = time.perf_counter()
        m["decode_round_seconds"].observe(now - t0)
        m["tokens"].inc(emitted, phase="decode")
        # per-request decode-round spans: each request in flight this
        # round gets one "decode_round" span on its trace lane (the
        # Chrome export shows the shared rounds lining up across rids);
        # round_traces was captured before evictions could retire them
        for tr in round_traces:
            if tr is not None:
                tr.add("decode_round", t0, now,
                       {"round": self._round, "chunk": self.chunk})
        self._round += 1

    def _finish(self, b: int):
        """Evict a finished row: pages back on the free list, table row
        to all-trash, slot open for backfill."""
        slot = self.slots[b]
        self._free_pages.extend(slot.pages)
        self.tables[b, :] = self.trash
        self.slots[b] = None
        self.finished[slot.req.rid] = slot.req
        req = slot.req
        req.t_finish = time.perf_counter()
        m = self._metrics
        m["requests"].inc(event="evicted")
        if len(req.new_tokens) > 1 and req.t_first_token:
            m["tpot"].observe((req.t_finish - req.t_first_token)
                              / (len(req.new_tokens) - 1))
        tr = self._live_traces.pop(req.rid, None)
        if tr is not None:
            sp = tr.end("decode", req.t_finish)
            if sp is not None:
                m["stage_seconds"].observe(sp.seconds, stage="decode")
            tr.meta["new_tokens"] = len(req.new_tokens)
            tr.add("e2e", req.t_submit, req.t_finish)
            m["stage_seconds"].observe(req.t_finish - req.t_submit,
                                       stage="e2e")
            self.traces.add(tr)

    # -- driving ---------------------------------------------------------
    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self.slots)

    def step(self):
        """One serving tick: admit arrivals, then one shared round —
        legacy mode prefills each arrival at admission and decodes the
        batch; chunked mode folds pending prompt chunks and decode rows
        into the unified dispatch (_chunked_round)."""
        self._admit()
        if self.chunked:
            self._chunked_round()
        else:
            self._decode_round()
        self._note_tick()

    def _note_tick(self):
        """Per-tick occupancy gauges + compile-counter deltas, then one
        registry snapshot into the stall flight-record ring."""
        m = self._metrics
        m["queue_depth"].set(len(self.queue))
        m["active_slots"].set(self.num_active)
        m["free_pages"].set(len(self._free_pages))
        usable = self.P - 1              # trash page is never allocable
        m["page_occupancy"].set(
            (usable - len(self._free_pages)) / usable if usable else 0.0)
        rc, rh = self._stats_reported
        if self.stats.compiles > rc:
            m["compiles"].inc(self.stats.compiles - rc, site="serving")
        if self.stats.cache_hits > rh:
            m["cache_hits"].inc(self.stats.cache_hits - rh,
                                site="serving")
        self._stats_reported = (self.stats.compiles,
                                self.stats.cache_hits)
        if self._mem_on:
            lb = _ml.live_bytes()
            if lb:
                self._live_peak = max(self._live_peak, lb)
                m["mem_live"].set(lb)
                m["mem_live_peak"].set(self._live_peak)
        from ..observability import get_registry

        get_registry().snapshot()

    def _run_captured(self, site, fn, *args):
        """Run a compiled program under a comm-ledger capture: when the
        call traces (first execution) its static ledger is stored under
        ``site``; every execution republishes the stored ledger to the
        comm_bytes/comm_ops counters. Single-device programs record
        nothing and publish nothing. With the memory ledger on, the
        site's FIRST execution also stores an XLA memory_analysis of
        the same program (lowered BEFORE the call: the cache buffers
        are donated), republished as mem gauges per execution."""
        if self._mem_on and site not in self._mem_ledgers:
            self._mem_ledgers[site] = _ml.analyze(
                fn, args, program="_".join(str(s) for s in site))
        with _cl.capture() as cap:
            out = fn(*args)
        if len(cap):
            self._ledgers[site] = cap
        led = self._ledgers.get(site)
        if led is not None:
            led.publish(self._metrics["comm_bytes"],
                        self._metrics["comm_ops"])
        mled = self._mem_ledgers.get(site)
        if mled is not None:
            mled.publish(self._metrics)
        return out

    def comm_ledger(self, site) -> Optional[Any]:
        """Static comm ledger of a compiled serving program: site is
        ("decode",), ("prefill", seq_bucket), or ("unified",
        chunk_bucket) in chunked mode."""
        return self._ledgers.get(site)

    # -- memory accounting (observability/memledger) ---------------------
    def memory_ledger(self, site=("decode",)) -> Optional[Any]:
        """Static HBM memory ledger of a compiled serving program
        (site as in ``comm_ledger``); populated at the site's first
        execution when the engine was built with ``mem_ledger=True``
        (or PADDLE_TPU_MEM_LEDGER=1)."""
        return self._mem_ledgers.get(site)

    def memory_summary(self) -> Dict[str, Any]:
        """The serving memory section bench lines carry: every
        analyzed executable's byte classes plus the measured resident
        state (params + the KV page pool, with the per-page byte cost
        and pool geometry the "auto" sizing uses)."""
        mcfg = self.pred._model.config
        page_bytes = (2 * mcfg.num_layers * mcfg.num_kv_heads
                      * self.page * mcfg.head_dim
                      * np.dtype(self._dtype).itemsize)
        pool_bytes = sum(_ml.shard_bytes(kp) + _ml.shard_bytes(vp)
                         for kp, vp in self.pools)
        return {
            "executables": {led.program: led.to_dict()
                            for led in self._mem_ledgers.values()},
            "state": {
                "params_bytes": sum(_ml.shard_bytes(p._value)
                                    for p in self.pred._params),
                "kv_pool_bytes": pool_bytes,
                "page_bytes": page_bytes,
                "pool_pages": self.P,
                "live_peak_bytes": self._live_peak,
            },
        }

    def roofline_report(self):
        """Roofline verdict of the shared decode round
        (memledger.roofline): FLOPs from the 2N-per-token forward over
        the full B x chunk round, HBM traffic from the decode
        executable's memory ledger, ICI from its comm ledger's wire
        bytes, against the median measured round time. Serving decode
        is expected HBM-bound on chip (the weight-bandwidth roofline
        bench.py's decode lines report against)."""
        cfg = getattr(self.pred._model, "config", None)
        n_params = None
        fn = getattr(cfg, "num_params", None)
        if callable(fn):
            try:
                n_params = int(fn())
            except Exception:
                n_params = None
        if n_params is None:
            n_params = sum(
                int(np.prod(p._value.shape)) for p in self.pred._params)
        n_dev = max(jax.device_count(), 1)
        fl = 2.0 * n_params * self.B * self.chunk / n_dev
        led = self._mem_ledgers.get(("decode",))
        traffic = led.traffic_bytes if led is not None and \
            led.available else 0.0
        comm = self._ledgers.get(("decode",))
        wire = comm.bytes_for() if comm is not None else 0.0
        step_s = self._metrics["decode_round_seconds"].percentile(50)
        return _ml.roofline(
            step_seconds=step_s, flops_per_step=fl,
            hbm_traffic_bytes=traffic, wire_bytes=wire,
            device=jax.devices()[0], program="decode")

    # -- per-request traces ----------------------------------------------
    def request_traces(self) -> List[Dict[str, Any]]:
        """Finished request traces (bounded ring), oldest first — each
        with its queued/prefill/decode_round/decode/e2e spans."""
        return self.traces.to_dicts()

    def export_request_traces(self, path: Optional[str] = None
                              ) -> Dict[str, Any]:
        """Chrome-trace JSON (chrome://tracing / Perfetto) of the
        finished request traces plus any still in flight; writes to
        ``path`` when given and returns the trace dict."""
        return self.traces.to_chrome_trace(
            path, extra=list(self._live_traces.values()))

    def metrics_snapshot(self):
        """Current registry snapshot (TTFT/TPOT histograms, occupancy,
        counters) — the in-process API bench.py emits from."""
        self._note_tick()
        from ..observability import get_registry

        return get_registry().snapshot()

    def run(self, max_steps: Optional[int] = None
            ) -> Dict[int, ServingRequest]:
        """Drain the queue + in-flight batch; returns {rid: request}."""
        steps = 0
        while self.queue or self.num_active:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.finished
