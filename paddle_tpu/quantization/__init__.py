"""Quantization: QAT (fake quant with STE) + PTQ (observers).

(reference: python/paddle/quantization/ — QuantConfig config.py, QAT
qat.py, PTQ ptq.py, observers in observer.py, fake quanters in
quanter.py; CUDA fake-quant kernels fluid/operators/fake_quantize_op.*.)

TPU-native: fake-quant is a pure jnp simulation (scale/round/clip/
rescale) with a straight-through-estimator gradient, so QAT runs inside
compiled training steps; PTQ observers collect absmax ranges during
eager/compiled calibration forwards and ``convert`` bakes the scales
into Quanted layers.
"""
from __future__ import annotations

import copy
from typing import Dict, Optional, Type

import jax
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import def_grad, def_op
from ..nn import functional as F
from ..nn.common import Linear
from ..nn.conv import Conv2D
from ..nn.layer import Layer
from ..tensor import Tensor

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "FakeQuanterWithAbsMaxObserver", "QuantedLinear",
           "QuantedConv2D", "Int8Linear", "quant_dequant"]


@def_op("fake_quantize_dequantize_abs_max")
def _fake_qdq(x, scale, bit_length=8):
    """Simulated quantization q(x) = round(x/s * qmax)/qmax * s."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q / qmax * s


@def_grad("fake_quantize_dequantize_abs_max")
def _fake_qdq_grad(in_values, out_values, out_grads, **attrs):
    # straight-through estimator: d out / d x = 1 inside the clip range
    x, scale = in_values[0], in_values[1]
    g = out_grads if not isinstance(out_grads, (tuple, list)) \
        else out_grads[0]
    s = jnp.maximum(scale, 1e-8)
    inside = jnp.abs(x) <= s
    gx = jnp.where(inside, g, jnp.zeros((), g.dtype))
    return tuple([gx] + [None] * (len(in_values) - 1))


def quant_dequant(x, scale, bit_length: int = 8):
    """Public fake quant-dequant (STE gradient)."""
    if not isinstance(scale, Tensor):
        scale = Tensor(jnp.asarray(scale, jnp.float32))
    return _fake_qdq(x, scale, bit_length)


class AbsmaxObserver(Layer):
    """PTQ range observer (reference observer.py AbsmaxObserver)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits
        self._max = 0.0

    def forward(self, x):
        self._max = max(self._max,
                        float(jnp.max(jnp.abs(x._value))))
        return x

    def scales(self) -> float:
        return self._max if self._max > 0 else 1e-8

    def _instance(self, layer):
        return AbsmaxObserver(self.quant_bits)


class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT fake quanter with moving-average absmax
    (reference quanter.py FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 **kw):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self._scale = 1.0

    def forward(self, x):
        cur = float(jnp.max(jnp.abs(jax.lax.stop_gradient(x._value)))) \
            if not self._is_traced(x) else None
        if cur is not None:
            r = self.moving_rate
            self._scale = r * self._scale + (1 - r) * cur \
                if self._scale != 1.0 or cur == 0 else cur
        return quant_dequant(x, self._scale, self.bit_length)

    @staticmethod
    def _is_traced(x):
        return isinstance(x._value, jax.core.Tracer)

    def scales(self) -> float:
        return self._scale

    def _instance(self, layer):
        return FakeQuanterWithAbsMaxObserver(self.moving_rate,
                                             self.bit_length)


class QuantConfig:
    """(reference config.py QuantConfig) — default + per-type/per-layer
    activation/weight quanter prototypes."""

    def __init__(self, activation=None, weight=None):
        self.default = (activation, weight)
        self._type_cfg: Dict[Type, tuple] = {}
        self._layer_cfg: Dict[int, tuple] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_cfg[t] = (activation, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_cfg[id(l)] = (activation, weight)

    def config_for(self, layer):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        return self.default


class QuantedLinear(Layer):
    """Linear with fake-quanted weight + activation (reference
    nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, inner: Linear, act_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.activation_quanter = act_quanter
        self.weight_quanter = w_quanter

    @property
    def weight(self):
        return self.inner.weight

    @property
    def bias(self):
        return self.inner.bias

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, inner: Conv2D, act_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.activation_quanter = act_quanter
        self.weight_quanter = w_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.conv2d(x, w, self.inner.bias, stride=self.inner._stride,
                        padding=self.inner._padding,
                        dilation=self.inner._dilation,
                        groups=self.inner._groups)


_QUANTABLE = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


def _wrap_model(model: Layer, config: QuantConfig, inplace: bool):
    if not inplace:
        model = copy.deepcopy(model)
    for parent in model.sublayers(include_self=True):
        for name, child in list(parent.named_children()):
            qcls = _QUANTABLE.get(type(child))
            if qcls is None:
                continue
            act_p, w_p = config.config_for(child)
            act = act_p._instance(child) if act_p is not None else None
            wq = w_p._instance(child) if w_p is not None else None
            if act is None and wq is None:
                continue
            setattr(parent, name, qcls(child, act, wq))
    return model


class QAT:
    """Quantization-aware training (reference qat.py QAT)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        return _wrap_model(model, self.config, inplace)


class PTQ:
    """Post-training quantization (reference ptq.py PTQ): quantize()
    inserts observers; run calibration forwards; convert() freezes the
    observed scales into fake-quant layers."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        return _wrap_model(model, self.config, inplace)

    def convert(self, model: Layer, inplace: bool = True,
                to_int8: bool = False) -> Layer:
        """Freeze observed scales. ``to_int8=True`` additionally swaps
        every QuantedLinear for an :class:`Int8Linear` — int8 weights +
        int8 MXU matmul, the deployable export (reference
        save_quantized_model path)."""
        if not inplace:
            model = copy.deepcopy(model)
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                for attr in ("activation_quanter", "weight_quanter"):
                    q = getattr(layer, attr)
                    if isinstance(q, AbsmaxObserver):
                        setattr(layer, attr,
                                _FrozenQuant(q.scales(), q.quant_bits))
        if to_int8:
            _ptq_convert_int8(model)
        return model


class _FrozenQuant(Layer):
    def __init__(self, scale: float, bits: int):
        super().__init__()
        self.scale = scale
        self.bits = bits

    def forward(self, x):
        return quant_dequant(x, self.scale, self.bits)

    def scales(self):
        return self.scale


class Int8Linear(Layer):
    """True-int8 inference Linear (the export target of PTQ convert
    (to_int8=True)): weight stored as int8 + per-tensor scale;
    activations quantize to int8 at the frozen calibration scale; the
    matmul runs int8 x int8 -> int32 on the MXU (TPU int8 throughput is
    2x bf16), rescaled back to float once.

    (reference: the inference-side dequant of
    fluid/inference passes + phi quantize_linear kernels — there the
    int8 path targets DP4A/cuBLASLt; here lax.dot_general with int8
    operands and int32 accumulation.)"""

    def __init__(self, inner: Linear, act_scale: float, w_scale: float,
                 bits: int = 8):
        super().__init__()
        qmax = float(2 ** (bits - 1) - 1)
        self.qmax = qmax
        self.act_scale = float(act_scale)
        self.w_scale = float(w_scale)
        w = inner.weight._value.astype(jnp.float32)
        self.weight_int8 = Tensor(jnp.clip(
            jnp.round(w / max(self.w_scale, 1e-8) * qmax),
            -qmax, qmax).astype(jnp.int8), stop_gradient=True)
        self.bias = inner.bias

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else x
        dt = xv.dtype
        qx = jnp.clip(jnp.round(
            xv.astype(jnp.float32) / max(self.act_scale, 1e-8)
            * self.qmax), -self.qmax, self.qmax).astype(jnp.int8)
        acc = jax.lax.dot_general(
            qx, self.weight_int8._value,
            (((qx.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (
            self.act_scale * self.w_scale / (self.qmax * self.qmax))
        if self.bias is not None:
            out = out + self.bias._value.astype(jnp.float32)
        return Tensor(out.astype(dt), stop_gradient=True)


def _ptq_convert_int8(model: Layer) -> Layer:
    """Swap every QuantedLinear for an Int8Linear, in place."""
    def replace(layer):
        for name in list(layer._sub_layers):
            sub = layer._sub_layers[name]
            if isinstance(sub, QuantedLinear):
                a = sub.activation_quanter
                w = sub.weight_quanter
                if a is None or w is None:
                    # weight- or act-only config: int8 matmul needs BOTH
                    # scales; keep the fake-quant layer as converted
                    continue
                layer._sub_layers[name] = Int8Linear(
                    sub.inner, float(a.scales()), float(w.scales()))
            else:
                replace(sub)
    replace(model)
    return model
