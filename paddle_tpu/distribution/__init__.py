"""Probability distributions (reference: python/paddle/distribution/ —
Distribution base, Normal, Uniform, Categorical, Bernoulli, Beta,
Dirichlet, Multinomial, kl_divergence registry).

Sampling draws from the framework's global RNG (core/rng) so
paddle.seed governs reproducibility, and every density is a jnp
expression — usable inside compiled steps (policy-gradient losses etc.).
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp

from ..core import rng as _rng
from ..tensor import Tensor, to_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Multinomial", "Exponential", "Gumbel",
           "Laplace", "LogNormal", "kl_divergence",
           "register_kl"]


def _tape_through(name, fn, *args):
    """Run a pure-jnp fn over mixed Tensor/array args, recording a
    replayable tape node so ``backward()`` flows into the Tensor args.

    Uses the engine's _TapedFnNode (the pure-fn/vjp-at-apply node): it
    filters jax float0 cotangents (integer-valued inputs, e.g. a
    Categorical's values) and supports create_graph re-taping, so
    higher-order gradients through densities/transforms work."""
    from ..autograd import engine

    tensors = [a if isinstance(a, Tensor) else None for a in args]
    vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
            for a in args]
    out_val = fn(*vals)
    track = engine.is_grad_enabled() and any(
        t is not None and not t.stop_gradient for t in tensors)
    out = Tensor(out_val, stop_gradient=not track)
    if track:
        edges = []
        for t in tensors:
            if t is None or t.stop_gradient:
                edges.append(None)
            elif t._grad_node is not None:
                edges.append(("node", t._grad_node, t._out_idx))
            else:
                edges.append(("leaf", t))
        node = engine._TapedFnNode(name, lambda *a: (fn(*a),), vals,
                                   (out_val,), edges)
        out._grad_node = node
        out._out_idx = 0
    return out


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32)


def _key():
    return _rng.get_key()


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def __init_subclass__(cls, **kw):
        """Make every family's ``log_prob`` tape-differentiable w.r.t.
        ``value`` in ONE place: the subclass impls are pure jnp math
        (scipy-parity), so a requires-grad input routes through
        ``_tape_through`` (jax.vjp recorded as a custom tape node) and
        ``loss.backward()`` through log_prob works — the score-matching
        / VAE-reconstruction path of the reference's op-built
        distributions. Gradients w.r.t. distribution PARAMETERS require
        parameters kept as live network outputs (reference dygraph);
        here constructor params are frozen arrays by design."""
        super().__init_subclass__(**kw)
        impl = cls.__dict__.get("log_prob")
        if impl is not None:
            def log_prob(self, value, _impl=impl, _cls=cls):
                from ..autograd import engine as _eng

                if (isinstance(value, Tensor) and not value.stop_gradient
                        and _eng.is_grad_enabled()):
                    return _tape_through(
                        f"{_cls.__name__}_log_prob",
                        lambda v: _impl(self, Tensor(
                            v, stop_gradient=True))._value,
                        value)
                return _impl(self, value)

            cls.log_prob = log_prob

    def prob(self, value):
        # dispatched exp keeps the taped log_prob's gradient path alive
        from ..ops import math as _m

        return _m.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        eps = jax.random.normal(_key(), shp)
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        v = _val(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros(self._batch_shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))


class LogNormal(Normal):
    def sample(self, shape=()):
        return Tensor(jnp.exp(super().sample(shape)._value))

    def log_prob(self, value):
        v = _val(value)
        lv = jnp.log(v)
        base = super().log_prob(Tensor(lv))._value
        return Tensor(base - lv)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_key(), shp)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low)
                      + jnp.zeros(self._batch_shape))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None and probs is None:
            self.logits = _val(logits)
        else:
            self.logits = jnp.log(jnp.clip(_val(probs), 1e-30))
        self._probs = jax.nn.softmax(self.logits, -1)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor(self._probs)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.categorical(
            _key(), self.logits, axis=-1, shape=shp))

    def log_prob(self, value):
        v = _val(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(jnp.take_along_axis(
            logp, v[..., None], axis=-1)[..., 0])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(self._probs * logp, -1))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self._probs = _val(probs)
            self.logits = jnp.log(self._probs / (1 - self._probs))
        else:
            self.logits = _val(logits)
            self._probs = jax.nn.sigmoid(self.logits)
        super().__init__(self._probs.shape)

    @property
    def probs(self):
        return Tensor(self._probs)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            _key(), self._probs, shape=shp).astype(jnp.float32))

    def log_prob(self, value):
        v = _val(value)
        p = jnp.clip(self._probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self._probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.beta(_key(), self.alpha, self.beta, shp))

    def log_prob(self, value):
        v = _val(value)
        from jax.scipy.special import betaln

        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v)
                      - betaln(self.alpha, self.beta))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma

        a, b = self.alpha, self.beta
        return Tensor(betaln(a, b) - (a - 1) * digamma(a)
                      - (b - 1) * digamma(b)
                      + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.dirichlet(
            _key(), self.concentration, shp))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _val(value)
        a = self.concentration
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1)
                      + gammaln(jnp.sum(a, -1)) - jnp.sum(gammaln(a), -1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self._probs = _val(probs)
        super().__init__(self._probs.shape[:-1], self._probs.shape[-1:])

    def sample(self, shape=()):
        n = self._probs.shape[-1]
        shp = tuple(shape) + self._batch_shape
        draws = jax.random.categorical(
            _key(), jnp.log(jnp.clip(self._probs, 1e-30)), axis=-1,
            shape=(self.total_count,) + shp)
        counts = jax.nn.one_hot(draws, n).sum(0)
        return Tensor(counts)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _val(value)
        logp = jnp.log(jnp.clip(self._probs, 1e-30))
        return Tensor(gammaln(self.total_count + 1.0)
                      - jnp.sum(gammaln(v + 1.0), -1)
                      + jnp.sum(v * logp, -1))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.exponential(_key(), shp) / self.rate)

    def log_prob(self, value):
        v = _val(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.gumbel(_key(), shp))

    def log_prob(self, value):
        z = (_val(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.laplace(_key(), shp))

    def log_prob(self, value):
        v = _val(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1.0 + jnp.log(2 * self.scale))


# -- KL divergence registry ----------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"KL({type(p).__name__} || {type(q).__name__}) not registered")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_p, var_q = p.scale ** 2, q.scale ** 2
    return Tensor(jnp.log(q.scale / p.scale)
                  + (var_p + (p.loc - q.loc) ** 2) / (2 * var_q) - 0.5)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp = jnp.clip(p._probs, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q._probs, 1e-7, 1 - 1e-7)
    return Tensor(pp * (jnp.log(pp) - jnp.log(qq))
                  + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


# distribution tail: transforms + Gamma/Poisson/Binomial/... (extra.py)
from .extra import *  # noqa: F401,F403,E402
from . import extra as transform  # noqa: F401,E402  (paddle.distribution.transform module alias)
__all__ = __all__ + list(transform.__all__)  # noqa: E402
