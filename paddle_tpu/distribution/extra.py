"""Distribution tail (reference: python/paddle/distribution/ — the
transform family transform.py, Gamma/Poisson/Binomial/Geometric/Cauchy/
ContinuousBernoulli/MultivariateNormal distributions,
TransformedDistribution, Independent). Same conventions as __init__:
global-RNG sampling, jnp densities usable inside compiled steps.
"""
from __future__ import annotations

import math

import jax
import numpy as np
import jax.numpy as jnp

from ..core import rng as _rng
from ..core.enforce import enforce
from ..tensor import Tensor
from . import Distribution, _key, _val

__all__ = [
    "ExponentialFamily", "Gamma", "Poisson", "Binomial", "Geometric",
    "Cauchy", "ContinuousBernoulli", "MultivariateNormal", "Independent",
    "TransformedDistribution",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class ExponentialFamily(Distribution):
    """Base marker for exponential-family members (reference:
    distribution/exponential_family.py; entropy via Bregman identity is
    specialized in subclasses here)."""


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate):
        self.concentration = _val(concentration)
        self.rate = _val(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        g = jax.random.gamma(_key(), jnp.broadcast_to(
            self.concentration, shp))
        return Tensor(g / self.rate)

    rsample = sample

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _val(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - gammaln(a))

    def entropy(self):
        from jax.scipy.special import digamma, gammaln

        a, b = self.concentration, self.rate
        return Tensor(a - jnp.log(b) + gammaln(a)
                      + (1 - a) * digamma(a)
                      + jnp.zeros(self._batch_shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.concentration / self.rate,
                                       self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            self.concentration / self.rate ** 2, self._batch_shape))


class Poisson(ExponentialFamily):
    def __init__(self, rate):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.poisson(
            _key(), jnp.broadcast_to(self.rate, shp)).astype(jnp.float32))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _val(value)
        return Tensor(v * jnp.log(self.rate) - self.rate
                      - gammaln(v + 1.0))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.rate, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.rate, self._batch_shape))


class Binomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = _val(total_count)
        self.probs = _val(probs)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.total_count), self.probs.shape))

    def sample(self, shape=()):
        from ..ops.extra import _binomial

        shp = tuple(shape) + self._batch_shape
        n = jnp.broadcast_to(self.total_count, shp)
        p = jnp.broadcast_to(self.probs, shp)
        nmax = int(np.asarray(self.total_count).max())
        return Tensor(_binomial.raw(_key(), n, p, nmax)
                      .astype(jnp.float32))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _val(value)
        n, p = self.total_count, self.probs
        logc = gammaln(n + 1) - gammaln(v + 1) - gammaln(n - v + 1)
        return Tensor(logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.total_count * self.probs,
                                       self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            self.total_count * self.probs * (1 - self.probs),
            self._batch_shape))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before success)."""

    def __init__(self, probs):
        self.probs = _val(probs)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_key(), shp, minval=1e-20)
        return Tensor(jnp.floor(jnp.log(u)
                                / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _val(value)
        return Tensor(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to((1 - self.probs) / self.probs,
                                       self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            (1 - self.probs) / self.probs ** 2, self._batch_shape))


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.cauchy(_key(), shp))

    rsample = sample

    def log_prob(self, value):
        v = _val(value)
        z = (v - self.loc) / self.scale
        return Tensor(-jnp.log(math.pi * self.scale * (1 + z ** 2)))

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale)
                      + jnp.zeros(self._batch_shape))


class ContinuousBernoulli(Distribution):
    """(reference: distribution/continuous_bernoulli.py): density
    C(p) p^x (1-p)^(1-x) on [0, 1]."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = _val(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _log_const(self):
        p = self.probs
        # log C(p); the p ~ 0.5 limit is log 2 (series expansion)
        near = (p > self._lims[0]) & (p < self._lims[1])
        # safe replaces the near-0.5 band with 0.25, so 1-2*safe is
        # never ~0; arctanh(1-2p)/(1-2p) is positive for all p != 0.5
        safe = jnp.where(near, 0.25, p)
        c = jnp.log(2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe))
        return jnp.where(near, math.log(2.0), c)

    def log_prob(self, value):
        v = _val(value)
        return Tensor(self._log_const() + v * jnp.log(self.probs)
                      + (1 - v) * jnp.log1p(-self.probs))

    def sample(self, shape=()):
        # inverse CDF: F^-1(u) = log1p((2p-1)u/(1-p)) / log(p/(1-p));
        # the p ~ 0.5 limit is the uniform distribution
        shp = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_key(), shp, minval=1e-7, maxval=1 - 1e-7)
        p = jnp.broadcast_to(self.probs, shp)
        near = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near, 0.25, p)
        x = jnp.log1p((2 * safe - 1) * u / (1 - safe)) \
            / jnp.log(safe / (1 - safe))
        return Tensor(jnp.where(near, u, jnp.clip(x, 0.0, 1.0)))


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = _val(loc)
        enforce((covariance_matrix is None) != (scale_tril is None),
                "give exactly one of covariance_matrix / scale_tril")
        if scale_tril is not None:
            self._tril = _val(scale_tril)
        else:
            self._tril = jnp.linalg.cholesky(_val(covariance_matrix))
        d = self.loc.shape[-1]
        super().__init__(self.loc.shape[:-1], (d,))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape + self._event_shape
        eps = jax.random.normal(_key(), shp)
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i",
                                            self._tril, eps))

    rsample = sample

    def log_prob(self, value):
        v = _val(value)
        d = self._event_shape[0]
        diff = v - self.loc
        sol = jax.scipy.linalg.solve_triangular(
            self._tril, diff[..., None], lower=True)[..., 0]
        logdet = jnp.sum(jnp.log(jnp.abs(
            jnp.diagonal(self._tril, axis1=-2, axis2=-1))), -1)
        return Tensor(-0.5 * jnp.sum(sol ** 2, -1) - logdet
                      - 0.5 * d * math.log(2 * math.pi))

    def entropy(self):
        d = self._event_shape[0]
        logdet = jnp.sum(jnp.log(jnp.abs(
            jnp.diagonal(self._tril, axis1=-2, axis2=-1))), -1)
        return Tensor(0.5 * d * (1 + math.log(2 * math.pi)) + logdet
                      + jnp.zeros(self._batch_shape))

    @property
    def mean(self):
        return Tensor(self.loc)


class Independent(Distribution):
    """Reinterprets trailing batch dims as event dims (reference:
    distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._r = int(reinterpreted_batch_rank)
        b = base.batch_shape
        super().__init__(b[: len(b) - self._r],
                         b[len(b) - self._r:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._value
        return Tensor(jnp.sum(lp, axis=tuple(range(-self._r, 0))))

    def entropy(self):
        e = self.base.entropy()._value
        return Tensor(jnp.sum(e, axis=tuple(range(-self._r, 0))))


# ---------------------------------------------------------------------------
# transforms (reference: python/paddle/distribution/transform.py)
# ---------------------------------------------------------------------------
class Transform:
    """Bijection with log|det J| (reference transform.py Transform).

    The four public methods route requires-grad inputs through the
    autograd tape (``_tape_through``): subclasses implement pure-jnp
    ``_forward/_inverse/_fldj`` and gradients w.r.t. the VALUE come for
    free (normalizing-flow training), matching the reference's op-built
    transforms."""

    def _taped(self, name, impl, x):
        from . import _tape_through

        return _tape_through(f"{type(self).__name__}.{name}", impl, x)

    def forward(self, x):
        return self._taped("forward", self._forward,
                           x if isinstance(x, Tensor) else _val(x))

    def inverse(self, y):
        return self._taped("inverse", self._inverse,
                           y if isinstance(y, Tensor) else _val(y))

    def forward_log_det_jacobian(self, x):
        return self._taped("fldj", self._fldj,
                           x if isinstance(x, Tensor) else _val(x))

    def inverse_log_det_jacobian(self, y):
        return self._taped("ildj",
                           lambda v: -self._fldj(self._inverse(v)),
                           y if isinstance(y, Tensor) else _val(y))

    def __call__(self, x):
        return self.forward(x)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # one branch (reference returns the positive preimage)

    def _fldj(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _val(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-6, 1 - 1e-6))

    def _fldj(self, x):
        # log(1 - tanh(x)^2) = 2 (log 2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """Normalizes the last axis (not bijective; pseudo-inverse = log)."""

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError(
            "SoftmaxTransform is not a bijection; no log-det")


class StickBreakingTransform(Transform):
    """R^{d} -> interior of the d-simplex (reference transform.py)."""

    def _forward(self, x):
        offset = x.shape[-1] - jnp.cumsum(
            jnp.ones_like(x), axis=-1) + 1
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zc = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones_like(zc[..., :1]), zc[..., :-1]], -1)
        first = z * lead
        return jnp.concatenate([first, zc[..., -1:]], -1)

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], -1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros_like(ycum[..., :1]), ycum[..., :-1]], -1)
        z = y[..., :-1] / rest
        offset = z.shape[-1] - jnp.cumsum(
            jnp.ones_like(z), axis=-1) + 1
        return jnp.log(z / (1 - z)) + jnp.log(offset)

    def _fldj(self, x):
        offset = x.shape[-1] - jnp.cumsum(
            jnp.ones_like(x), axis=-1) + 1
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zc = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones_like(zc[..., :1]), zc[..., :-1]], -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(lead), -1)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._r = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        return jnp.sum(self.base._fldj(x),
                       axis=tuple(range(-self._r, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, x):
        lead = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def _fldj(self, x):
        lead = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead)


class StackTransform(Transform):
    """Applies a list of transforms to slices along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _apply(self, x, attr):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        out = [getattr(t, attr)(p.squeeze(self.axis))
               for t, p in zip(self.transforms, parts)]
        return jnp.stack(out, axis=self.axis)

    def _forward(self, x):
        return self._apply(x, "_forward")

    def _inverse(self, y):
        return self._apply(y, "_inverse")

    def _fldj(self, x):
        return self._apply(x, "_fldj")


class TransformedDistribution(Distribution):
    """(reference: distribution/transformed_distribution.py)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = (list(transforms)
                           if isinstance(transforms, (list, tuple))
                           else [transforms])
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)._value
        for t in self.transforms:
            x = t._forward(x)
        return Tensor(x)

    rsample = sample

    def log_prob(self, value):
        y = _val(value)
        ldj = 0.0
        for t in reversed(self.transforms):
            x = t._inverse(y)
            ldj = ldj + t._fldj(x)
            y = x
        return Tensor(self.base.log_prob(Tensor(y))._value - ldj)
