"""Vision: models/datasets/transforms (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
