"""Vision: models/datasets/transforms (reference: python/paddle/vision/)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401
