"""Detection/vision operators (paddle.vision.ops analog).

(reference: python/paddle/vision/ops.py over phi roi_align / roi_pool /
psroi_pool / nms / yolo_box / prior_box / box_coder /
distribute_fpn_proposals / deform_conv CUDA kernels.)

TPU design notes:
- roi_align / deform_conv2d are gather + bilinear-weight compositions —
  pure XLA HLOs, differentiable, jit/shard-compatible.
- roi_pool / psroi_pool use exact integer-quantized bins expressed as
  position masks with a fused where+reduce (XLA never materializes the
  masked copies).
- nms / distribute_fpn_proposals have data-dependent output SHAPES, so
  they run host-side on numpy by design (same stance as
  geometric.sampling); their outputs feed traced programs as inputs.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import nn
from ..core.dispatch import def_op
from ..core.enforce import enforce
from ..tensor import Tensor, to_tensor

__all__ = ["nms", "roi_align", "RoIAlign", "roi_pool", "RoIPool",
           "psroi_pool", "PSRoIPool", "box_coder", "yolo_box",
           "yolo_loss", "prior_box",
           "distribute_fpn_proposals", "deform_conv2d", "DeformConv2D",
           "ConvNormActivation", "read_file", "decode_jpeg"]


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (reference: vision/ops.py:1301)."""
    with open(filename, "rb") as f:
        return to_tensor(np.frombuffer(f.read(), np.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG decode (reference: vision/ops.py:1344 over nvjpeg). No JPEG
    codec ships in this environment; PIL is used when present."""
    try:
        import io

        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "decode_jpeg needs Pillow (no nvjpeg analog on TPU hosts); "
            "it is not available in this build") from e
    img = Image.open(io.BytesIO(_np(x).tobytes()))
    if mode != "unchanged":
        img = img.convert("L" if mode == "gray" else "RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return to_tensor(arr)


def _np(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


# ---------------------------------------------------------------------------
# NMS (host-side: kept-set size is data-dependent)
# ---------------------------------------------------------------------------
def _iou_matrix(b, normalized=True):
    off = 0.0 if normalized else 1.0  # pixel-coordinate +1 convention
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    area = (x2 - x1 + off) * (y2 - y1 + off)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    iw = np.clip(ix2 - ix1 + off, 0, None)
    ih = np.clip(iy2 - iy1 + off, 0, None)
    inter = iw * ih
    return inter / np.maximum(area[:, None] + area[None, :] - inter, 1e-10)


def _nms_single(boxes, scores, thr):
    order = np.argsort(-scores, kind="stable")
    iou = _iou_matrix(boxes)
    keep = []
    alive = np.ones(len(boxes), bool)
    for i in order:
        if not alive[i]:
            continue
        keep.append(i)
        alive &= iou[i] <= thr
        alive[i] = False
    return np.asarray(keep, np.int64)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Indices of boxes kept by (optionally per-category) NMS, sorted by
    descending score (reference: vision/ops.py:1867)."""
    b = _np(boxes).astype(np.float64)
    n = len(b)
    s = (_np(scores).astype(np.float64) if scores is not None
         else np.arange(n, 0, -1, dtype=np.float64))
    if category_idxs is None:
        keep = _nms_single(b, s, iou_threshold)
    else:
        cats = _np(category_idxs)
        enforce(categories is not None,
                "categories must accompany category_idxs")
        parts = []
        for c in categories:
            idx = np.nonzero(cats == c)[0]
            if len(idx):
                parts.append(idx[_nms_single(b[idx], s[idx],
                                             iou_threshold)])
        keep = np.concatenate(parts) if parts else np.zeros(0, np.int64)
        keep = keep[np.argsort(-s[keep], kind="stable")]
    if top_k is not None:
        keep = keep[: int(top_k)]
    return to_tensor(keep)


# ---------------------------------------------------------------------------
# RoI ops (traced, differentiable)
# ---------------------------------------------------------------------------
def _box_to_image(boxes_num):
    """Per-box image index from the per-image box counts (host)."""
    bn = _np(boxes_num).astype(np.int64)
    return np.repeat(np.arange(len(bn)), bn)


def _pair(v):
    return (int(v), int(v)) if np.isscalar(v) else (int(v[0]), int(v[1]))


@def_op("roi_align_kernel")
def _roi_align_kernel(x, boxes, box_im, ph, pw, spatial_scale,
                      sampling_ratio, aligned):
    N, C, H, W = x.shape
    off = 0.5 if aligned else 0.0
    bx = boxes.astype(jnp.float32) * spatial_scale - off
    x1, y1, x2, y2 = bx[:, 0], bx[:, 1], bx[:, 2], bx[:, 3]
    roi_w = x2 - x1
    roi_h = y2 - y1
    if not aligned:
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    sr = int(sampling_ratio)
    # sample grid: [ph*sr] x [pw*sr] per box
    def axis_points(start, extent, bins, s):
        # [B, bins*s] bilinear sample coordinates
        step = extent[:, None] / (bins * s)
        idx = jnp.arange(bins * s, dtype=jnp.float32)[None, :]
        return start[:, None] + (idx + 0.5) * step

    ys = axis_points(y1, roi_h, ph, sr)                  # [B, ph*sr]
    xs = axis_points(x1, roi_w, pw, sr)                  # [B, pw*sr]

    def bilinear_1d(coords, size):
        c = jnp.clip(coords, 0.0, size - 1.0)
        lo = jnp.floor(c)
        w_hi = c - lo
        lo = lo.astype(jnp.int32)
        hi = jnp.minimum(lo + 1, size - 1)
        return lo, hi, 1.0 - w_hi, w_hi

    ylo, yhi, wy0, wy1 = bilinear_1d(ys, H)
    xlo, xhi, wx0, wx1 = bilinear_1d(xs, W)
    feats = x[box_im]                                    # [B, C, H, W]

    def gather_y(rows):                                  # rows [B, S]
        return jnp.take_along_axis(
            feats, rows[:, None, :, None], axis=2)       # [B, C, S, W]

    def gather_xy(rows_g, cols):                         # -> [B, C, S, T]
        return jnp.take_along_axis(
            rows_g, cols[:, None, None, :], axis=3)

    top = gather_y(ylo)
    bot = gather_y(yhi)
    v = (gather_xy(top, xlo) * (wy0[:, None, :, None] * wx0[:, None, None, :])
         + gather_xy(top, xhi) * (wy0[:, None, :, None] * wx1[:, None, None, :])
         + gather_xy(bot, xlo) * (wy1[:, None, :, None] * wx0[:, None, None, :])
         + gather_xy(bot, xhi) * (wy1[:, None, :, None] * wx1[:, None, None, :]))
    B = boxes.shape[0]
    v = v.reshape(B, C, ph, sr, pw, sr)
    return v.mean(axis=(3, 5)).astype(x.dtype)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoI Align (Mask R-CNN): [num_boxes, C, ph, pw] bilinear-averaged
    box features (reference: vision/ops.py:1640).

    sampling_ratio=-1 deviation: the reference adapts the per-bin
    sample count per box (ceil(roi/bins)); a traced program needs ONE
    static grid, so the count is the largest box's need (host-read from
    the box values), clamped to 8 — denser than the reference for small
    boxes (more accurate), capped for huge ones."""
    ph, pw = _pair(output_size)
    box_im = _box_to_image(boxes_num)
    sr = int(sampling_ratio)
    if sr <= 0:
        b = _np(boxes).astype(np.float64) * float(spatial_scale)
        ext = np.maximum(np.maximum(b[:, 2] - b[:, 0],
                                    b[:, 3] - b[:, 1]), 1.0)
        sr = int(np.clip(np.ceil(ext.max() / max(ph, pw)) if len(b)
                         else 1, 1, 8))
    return _roi_align_kernel(x, boxes, jnp.asarray(box_im), ph, pw,
                             float(spatial_scale), sr, bool(aligned))


class RoIAlign(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


@def_op("roi_pool_kernel")
def _roi_pool_kernel(x, boxes, box_im, ph, pw, spatial_scale):
    N, C, H, W = x.shape
    bx = boxes.astype(jnp.float32) * spatial_scale
    x1 = jnp.round(bx[:, 0]).astype(jnp.int32)
    y1 = jnp.round(bx[:, 1]).astype(jnp.int32)
    x2 = jnp.round(bx[:, 2]).astype(jnp.int32)
    y2 = jnp.round(bx[:, 3]).astype(jnp.int32)
    roi_w = jnp.maximum(x2 - x1 + 1, 1)
    roi_h = jnp.maximum(y2 - y1 + 1, 1)

    def bin_bounds(start, extent, bins, size):
        i = jnp.arange(bins, dtype=jnp.float32)
        lo = jnp.floor(i[None, :] * extent[:, None] / bins)
        hi = jnp.ceil((i[None, :] + 1) * extent[:, None] / bins)
        lo = jnp.clip(start[:, None] + lo.astype(jnp.int32), 0, size)
        hi = jnp.clip(start[:, None] + hi.astype(jnp.int32), 0, size)
        return lo, hi                                     # [B, bins]

    hlo, hhi = bin_bounds(y1, roi_h.astype(jnp.float32), ph, H)
    wlo, whi = bin_bounds(x1, roi_w.astype(jnp.float32), pw, W)
    hpos = jnp.arange(H)[None, None, :]                   # [1, 1, H]
    wpos = jnp.arange(W)[None, None, :]
    hmask = (hpos >= hlo[:, :, None]) & (hpos < hhi[:, :, None])  # [B,ph,H]
    wmask = (wpos >= wlo[:, :, None]) & (wpos < whi[:, :, None])  # [B,pw,W]
    feats = x[box_im].astype(jnp.float32)                 # [B, C, H, W]
    neg = jnp.float32(-3.4e38)
    # fused where+max over H then W; empty bins fall back to 0
    t = jnp.where(hmask[:, None, :, :, None], feats[:, :, None], neg)
    t = t.max(axis=3)                                     # [B, C, ph, W]
    t = jnp.where(wmask[:, None, None, :, :], t[:, :, :, None], neg)
    t = t.max(axis=4)                                     # [B, C, ph, pw]
    empty = (~hmask.any(2))[:, None, :, None] | (~wmask.any(2))[:, None, None]
    return jnp.where(empty, 0.0, t).astype(x.dtype)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """RoI max-pool with integer-quantized bins (reference:
    vision/ops.py:1514)."""
    ph, pw = _pair(output_size)
    box_im = _box_to_image(boxes_num)
    return _roi_pool_kernel(x, boxes, jnp.asarray(box_im), ph, pw,
                            float(spatial_scale))


class RoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


@def_op("psroi_pool_kernel")
def _psroi_pool_kernel(x, boxes, box_im, ph, pw, spatial_scale):
    N, C, H, W = x.shape
    enforce(C % (ph * pw) == 0,
            lambda: f"psroi_pool needs channels ({C}) divisible by "
                    f"output_size^2 ({ph * pw})")
    out_c = C // (ph * pw)
    bx = boxes.astype(jnp.float32) * spatial_scale
    x1, y1, x2, y2 = bx[:, 0], bx[:, 1], bx[:, 2], bx[:, 3]
    roi_w = jnp.maximum(x2 - x1, 0.1)
    roi_h = jnp.maximum(y2 - y1, 0.1)

    def bin_bounds(start, extent, bins, size):
        i = jnp.arange(bins, dtype=jnp.float32)
        lo = jnp.floor(start[:, None] + i[None, :] * extent[:, None] / bins)
        hi = jnp.ceil(start[:, None]
                      + (i[None, :] + 1) * extent[:, None] / bins)
        return (jnp.clip(lo, 0, size).astype(jnp.int32),
                jnp.clip(hi, 0, size).astype(jnp.int32))

    hlo, hhi = bin_bounds(y1, roi_h, ph, H)
    wlo, whi = bin_bounds(x1, roi_w, pw, W)
    hpos = jnp.arange(H)[None, None, :]
    wpos = jnp.arange(W)[None, None, :]
    hmask = (hpos >= hlo[:, :, None]) & (hpos < hhi[:, :, None])
    wmask = (wpos >= wlo[:, :, None]) & (wpos < whi[:, :, None])
    B = boxes.shape[0]
    # channel layout: channel (c_out * ph + i) * pw + j feeds bin (i, j)
    feats = x[box_im].reshape(B, out_c, ph, pw, H, W).astype(jnp.float32)
    m = (hmask[:, None, :, None, :, None]
         & wmask[:, None, None, :, None, :])
    s = jnp.where(m, feats, 0.0).sum(axis=(4, 5))
    cnt = m.sum(axis=(4, 5)).astype(jnp.float32)
    return (s / jnp.maximum(cnt, 1.0)).astype(x.dtype)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pool (R-FCN; reference:
    vision/ops.py:1393)."""
    ph, pw = _pair(output_size)
    box_im = _box_to_image(boxes_num)
    return _psroi_pool_kernel(x, boxes, jnp.asarray(box_im), ph, pw,
                              float(spatial_scale))


class PSRoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


# ---------------------------------------------------------------------------
# Box coding / anchors / YOLO decode (traced)
# ---------------------------------------------------------------------------
@def_op("box_coder")
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Encode/decode boxes against priors (reference: vision/
    ops.py:573; phi box_coder kernel)."""
    pb = prior_box.astype(jnp.float32)
    tb = target_box.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph_ = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph_ * 0.5
    if prior_box_var is not None:
        var = prior_box_var.astype(jnp.float32)
        if var.ndim == 1:
            var = jnp.broadcast_to(var[None, :], pb.shape)
    else:
        var = jnp.ones_like(pb)
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph_[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph_[None, :]),
        ], axis=-1) / var[None, :, :]
        return out                                  # [T, P, 4]
    # decode_center_size: target [P, 4] or [P, M, 4] deltas
    enforce(code_type == "decode_center_size",
            lambda: f"unknown code_type {code_type!r}")
    t = tb if tb.ndim == 3 else tb[:, None, :]
    if axis == 0:
        pcx_, pcy_, pw_, ph2 = (pcx[:, None], pcy[:, None],
                                pw[:, None], ph_[:, None])
        v = var[:, None, :]
    else:
        pcx_, pcy_, pw_, ph2 = (pcx[None, :], pcy[None, :],
                                pw[None, :], ph_[None, :])
        v = var[None, :, :]
    dcx = v[..., 0] * t[..., 0] * pw_ + pcx_
    dcy = v[..., 1] * t[..., 1] * ph2 + pcy_
    dw = jnp.exp(v[..., 2] * t[..., 2]) * pw_
    dh = jnp.exp(v[..., 3] * t[..., 3]) * ph2
    out = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                     dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm],
                    axis=-1)
    return out if tb.ndim == 3 else out[:, 0, :]


@def_op("yolo_box")
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output into boxes+scores (reference: vision/
    ops.py:266; phi yolo_box kernel). Returns (boxes [N, H*W*na, 4],
    scores [N, H*W*na, class_num])."""
    anchors = list(anchors)
    na = len(anchors) // 2
    N, C, H, W = x.shape
    xin = x.astype(jnp.float32)
    if iou_aware:
        # iou-aware head layout (GetIoUIndex, yolo_box_util.h:67): the
        # first na channels are iou logits, the rest the standard head
        ioup = jax.nn.sigmoid(xin[:, :na])            # [N, na, H, W]
        xin = xin[:, na:]
    xf = xin.reshape(N, na, (C - (na if iou_aware else 0)) // na, H, W)
    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(xf[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_x) / W
    by = (jax.nn.sigmoid(xf[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_y) / H
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    in_w = float(downsample_ratio * W)
    in_h = float(downsample_ratio * H)
    bw = jnp.exp(xf[:, :, 2]) * aw / in_w
    bh = jnp.exp(xf[:, :, 3]) * ah / in_h
    conf = jax.nn.sigmoid(xf[:, :, 4])
    if iou_aware:
        # conf^(1-f) * iou^f (cpu/yolo_box_kernel.cc:85)
        conf = (conf ** (1.0 - iou_aware_factor)) * \
            (ioup ** iou_aware_factor)
    probs = jax.nn.sigmoid(xf[:, :, 5:5 + class_num])
    score = conf[:, :, None] * probs
    keep = conf > conf_thresh
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, img_w - 1)
        y2 = jnp.minimum(y2, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    score = jnp.where(keep[:, :, None], score, 0.0)
    # both flatten anchor-major, i.e. (anchor, h, w) row order — the
    # reference kernel's box_idx = ((i*box_num + j)*stride + k*w + l)
    # with j=anchor — so row i here pairs with the reference's row i
    # (index-based consumers, exported postprocessing)
    boxes = boxes.reshape(N, -1, 4)                       # [N,na,H,W,4]
    score = score.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
    return boxes, score


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes for one feature map (reference: vision/
    ops.py:427). Host-built constants: anchors depend only on shapes."""
    _, _, H, W = (input.shape if not isinstance(input, Tensor)
                  else input._value.shape)
    _, _, img_h, img_w = (image.shape if not isinstance(image, Tensor)
                          else image._value.shape)
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    num_priors = len(ars) * len(min_sizes) + (len(max_sizes or []))
    # per-prior half extents (bw, bh) in the reference's emission order
    half_w, half_h = [], []
    for i, ms in enumerate(min_sizes):
        per_min = []
        for ar in ars:
            per_min.append((ms * np.sqrt(ar) / 2, ms / np.sqrt(ar) / 2))
        if max_sizes is not None:
            s = np.sqrt(ms * max_sizes[i]) / 2
            if min_max_aspect_ratios_order:
                # caffe order: [min, max, other ars]
                # (cpu/prior_box_kernel.cc:77)
                per_min = per_min[:1] + [(s, s)] + per_min[1:]
            else:
                per_min = per_min + [(s, s)]
        half_w += [p[0] for p in per_min]
        half_h += [p[1] for p in per_min]
    hw = np.asarray(half_w, np.float32)[None, None, :]
    hh = np.asarray(half_h, np.float32)[None, None, :]
    cx = ((np.arange(W, dtype=np.float32) + offset)
          * step_w)[None, :, None]
    cy = ((np.arange(H, dtype=np.float32) + offset)
          * step_h)[:, None, None]
    out = np.stack(
        np.broadcast_arrays((cx - hw) / img_w, (cy - hh) / img_h,
                            (cx + hw) / img_w, (cy + hh) / img_h),
        axis=-1).astype(np.float32)              # [H, W, P, 4]
    var = np.tile(np.asarray(variance, np.float32),
                  (H, W, num_priors, 1))
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return to_tensor(out), to_tensor(var)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Split RoIs across FPN levels by scale (reference: vision/
    ops.py:1156). Host-side: per-level counts are data-dependent."""
    rois = _np(fpn_rois).astype(np.float64)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.clip(w * h, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois, restore_parts = [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        multi_rois.append(to_tensor(rois[idx].astype(np.float32)))
        restore_parts.append(idx)
    order = np.concatenate(restore_parts) if restore_parts else \
        np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    rois_num_per_level = None
    if rois_num is not None:
        rn = _np(rois_num).astype(np.int64)
        img_of = np.repeat(np.arange(len(rn)), rn)
        rois_num_per_level = [
            to_tensor(np.bincount(img_of[lvl == L], minlength=len(rn))
                      .astype(np.int32))
            for L in range(min_level, max_level + 1)]
    return multi_rois, to_tensor(restore[:, None]), rois_num_per_level


# ---------------------------------------------------------------------------
# Deformable convolution (gather + bilinear; traced, differentiable)
# ---------------------------------------------------------------------------
@def_op("deform_conv2d_kernel")
def _deform_conv2d_kernel(x, offset, weight, mask, stride, padding,
                          dilation, deformable_groups):
    N, C, H, W = x.shape
    out_c, in_c_g, kh, kw = weight.shape
    sh, sw = stride
    ph_, pw_ = padding
    dh, dw = dilation
    out_h = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    out_w = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
    xf = jnp.pad(x.astype(jnp.float32),
                 ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))
    Hp, Wp = H + 2 * ph_, W + 2 * pw_
    off = offset.astype(jnp.float32).reshape(
        N, deformable_groups, kh * kw, 2, out_h, out_w)
    dy = off[:, :, :, 0]                                 # [N, dg, khkw, oh, ow]
    dx = off[:, :, :, 1]
    k_idx = jnp.arange(kh * kw)
    ky, kx = k_idx // kw, k_idx % kw
    # sample positions per (n, dg, k, oh, ow)
    pos_y = (jnp.arange(out_h) * sh)[None, None, None, :, None] \
        + (ky * dh)[None, None, :, None, None] + dy
    pos_x = (jnp.arange(out_w) * sw)[None, None, None, None, :] \
        + (kx * dw)[None, None, :, None, None] + dx

    y0 = jnp.floor(pos_y)
    x0 = jnp.floor(pos_x)
    wy1 = pos_y - y0
    wx1 = pos_x - x0
    y0i = jnp.clip(y0.astype(jnp.int32), 0, Hp - 1)
    y1i = jnp.clip(y0.astype(jnp.int32) + 1, 0, Hp - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, Wp - 1)
    x1i = jnp.clip(x0.astype(jnp.int32) + 1, 0, Wp - 1)
    inb = ((pos_y > -1) & (pos_y < Hp) & (pos_x > -1) & (pos_x < Wp)) \
        .astype(jnp.float32)

    cg = C // deformable_groups
    xg = xf.reshape(N, deformable_groups, cg, Hp, Wp)
    flat = xg.reshape(N, deformable_groups, cg, Hp * Wp)

    def take(yi, xi):
        lin = yi * Wp + xi                               # [N,dg,k,oh,ow]
        lin_ = lin.reshape(N, deformable_groups, 1, -1)
        g = jnp.take_along_axis(
            flat, jnp.broadcast_to(lin_, (N, deformable_groups, cg,
                                          lin_.shape[-1])), axis=3)
        return g.reshape(N, deformable_groups, cg, kh * kw, out_h, out_w)

    w00 = ((1 - wy1) * (1 - wx1))[:, :, None]
    w01 = ((1 - wy1) * wx1)[:, :, None]
    w10 = (wy1 * (1 - wx1))[:, :, None]
    w11 = (wy1 * wx1)[:, :, None]
    val = (take(y0i, x0i) * w00 + take(y0i, x1i) * w01
           + take(y1i, x0i) * w10 + take(y1i, x1i) * w11)
    val = val * inb[:, :, None]
    if mask is not None:
        m = mask.astype(jnp.float32).reshape(
            N, deformable_groups, 1, kh * kw, out_h, out_w)
        val = val * m
    cols = val.reshape(N, C * kh * kw, out_h, out_w)
    wcol = weight.astype(jnp.float32).reshape(out_c, in_c_g * kh * kw)
    groups = C // in_c_g
    cols = cols.reshape(N, groups, in_c_g * kh * kw, out_h, out_w)
    wg = wcol.reshape(groups, out_c // groups, in_c_g * kh * kw)
    out = jnp.einsum("ngkhw,gok->ngohw", cols, wg)
    return out.reshape(N, out_c, out_h, out_w).astype(x.dtype)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference: vision/ops.py:753) —
    bilinear sampling at learned offsets then a grouped matmul; the
    gathers and interpolation weights are all XLA HLOs."""
    C = (x._value if isinstance(x, Tensor) else x).shape[1]
    in_c_g = (weight._value if isinstance(weight, Tensor)
              else weight).shape[1]
    enforce(int(groups) * in_c_g == C,
            lambda: f"deform_conv2d: groups ({groups}) disagrees with "
                    f"the weight layout — in_channels ({C}) / "
                    f"weight.shape[1] ({in_c_g}) = {C // in_c_g} groups "
                    "(the kernel derives its grouping from the shapes, "
                    "so a mismatched knob would be silently ignored)")
    st = _pair(stride)
    pd = _pair(padding)
    dl = _pair(dilation)
    out = _deform_conv2d_kernel(x, offset, weight, mask, st, pd, dl,
                                int(deformable_groups))
    return out if bias is None else out + bias.reshape([1, -1, 1, 1])


class DeformConv2D(nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = _pair(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw),
            attr=weight_attr)
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


class ConvNormActivation(nn.Sequential):
    """Conv2D + norm + activation block (reference: vision/
    ops.py:1810)."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=nn.BatchNorm2D,
                 activation_layer=nn.ReLU, dilation=1, bias=None):
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                            padding, dilation=dilation, groups=groups,
                            bias_attr=bias)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2; reference: vision/ops.py:2236 over the phi
    matrix_nms kernel). Decay-based soft suppression: each candidate's
    score decays by the IoU with every higher-scored same-class box.
    Host-side: kept count is data-dependent.

    bboxes [N, M, 4], scores [N, C, M]. Returns Out [K, 6] rows of
    (label, score, x1, y1, x2, y2) (+ index / rois_num like the
    reference).
    """
    bx = _np(bboxes).astype(np.float64)
    sc = _np(scores).astype(np.float64)
    N, C, M = sc.shape
    all_rows, all_idx, rois_num = [], [], []
    for n in range(N):
        rows, idxs = [], []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            keep = np.nonzero(s > score_threshold)[0]
            if len(keep) == 0:
                continue
            order = keep[np.argsort(-s[keep], kind="stable")]
            if nms_top_k > -1:
                order = order[:nms_top_k]
            b = bx[n, order]
            sv = s[order].copy()
            iou = _iou_matrix(b, normalized)
            # decay[i] = min over higher-scored j of f(iou_ij)/f(max
            # iou of j with anything above it)
            K = len(order)
            iou_u = np.triu(iou, 1)
            # comp[i] = the SUPPRESSOR i's own max overlap with boxes
            # scored above it (matrix-nms compensation term)
            comp = iou_u.max(axis=0)
            if use_gaussian:
                # reference kernel (matrix_nms_kernel.cc:70):
                # exp((comp^2 - iou^2) * sigma) — multiplied, not
                # divided (deviates from the SOLOv2 paper's /sigma)
                decay = np.exp((comp[:, None] ** 2 - iou_u ** 2)
                               * gaussian_sigma)
            else:
                decay = (1 - iou_u) / np.maximum(1 - comp[:, None], 1e-10)
            decay = np.where(iou_u > 0, decay, 1.0)
            decay_min = decay.min(axis=0)
            sv = sv * decay_min
            ok = sv > post_threshold
            for i in np.nonzero(ok)[0]:
                rows.append([c, sv[i], *b[i]])
                idxs.append(n * M + order[i])
        if rows:
            rows = np.asarray(rows, np.float32)
            o = np.argsort(-rows[:, 1], kind="stable")
            if keep_top_k > -1:
                o = o[:keep_top_k]
            rows = rows[o]
            idxs = np.asarray(idxs, np.int64)[o]
        else:
            rows = np.zeros((0, 6), np.float32)
            idxs = np.zeros((0,), np.int64)
        all_rows.append(rows)
        all_idx.append(idxs)
        rois_num.append(len(rows))
    out = to_tensor(np.concatenate(all_rows) if all_rows
                    else np.zeros((0, 6), np.float32))
    result = [out]
    if return_index:
        result.append(to_tensor(np.concatenate(all_idx)))
    if return_rois_num:
        result.append(to_tensor(np.asarray(rois_num, np.int32)))
    return tuple(result) if len(result) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference: vision/ops.py:2038 over the
    phi generate_proposals kernel): decode deltas against anchors, clip
    to the image, drop tiny boxes, NMS. Host-side like nms."""
    sc = _np(scores).astype(np.float64)        # [N, A, H, W]
    dl = _np(bbox_deltas).astype(np.float64)   # [N, 4A, H, W]
    im = _np(img_size).astype(np.float64)      # [N, 2] (h, w)
    an = _np(anchors).astype(np.float64).reshape(-1, 4)
    var = _np(variances).astype(np.float64).reshape(-1, 4)
    enforce(eta >= 1.0, "adaptive NMS (eta < 1) is not supported here")
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    outs, out_scores, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        d = dl[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        order = np.argsort(-s, kind="stable")[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order], var[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw * 0.5
        acy = a[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        clip = np.log(1000.0 / 16.0)  # kBBoxClipDefault
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], clip)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], clip)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], 1)
        ih, iw = im[n]
        boxes[:, 0::2] = boxes[:, 0::2].clip(0, iw - off)
        boxes[:, 1::2] = boxes[:, 1::2].clip(0, ih - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        msz = max(float(min_size), 1.0)  # reference clamps to >= 1
        big = (ws >= msz) & (hs >= msz)
        if pixel_offset:
            # reference additionally requires the box CENTER in-image
            cxs = boxes[:, 0] + ws / 2
            cys = boxes[:, 1] + hs / 2
            big &= (cxs <= iw) & (cys <= ih)
        boxes, s = boxes[big], s[big]
        keep = _nms_single(boxes, s, nms_thresh)[:post_nms_top_n]
        outs.append(boxes[keep].astype(np.float32))
        out_scores.append(s[keep].astype(np.float32))
        nums.append(len(keep))
    rois = to_tensor(np.concatenate(outs) if outs
                     else np.zeros((0, 4), np.float32))
    rois_scores = to_tensor(np.concatenate(out_scores) if out_scores
                            else np.zeros((0,), np.float32))
    if return_rois_num:
        return rois, rois_scores, to_tensor(np.asarray(nums, np.int32))
    return rois, rois_scores


__all__ = __all__ + ["matrix_nms", "generate_proposals"]


@def_op("yolo_loss")
def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference: vision/ops.py:58 yolo_loss over
    phi yolo_loss kernel). TPU redesign: the per-gt anchor assignment
    and target scatter are vectorized jnp (scatter into [N,S,H,W]
    target maps) instead of the kernel's per-box loops; the three parts
    (sigmoid-CE xy + weighted L1 wh, objectness with IoU-ignore, and
    per-class sigmoid CE with label smoothing) match the reference
    formulation. Returns the per-image loss [N]."""
    anchors = [float(a) for a in anchors]
    amask = [int(m) for m in anchor_mask]
    S = len(amask)
    N, C, H, W = x.shape
    Bb = gt_box.shape[1]
    Cn = int(class_num)
    enforce(C == S * (5 + Cn),
            lambda: f"yolo_loss: C={C} != len(anchor_mask)*(5+class_num)"
                    f"={S * (5 + Cn)}")
    in_w = float(downsample_ratio * W)
    in_h = float(downsample_ratio * H)
    aw_all = jnp.asarray(anchors[0::2], jnp.float32)      # [A]
    ah_all = jnp.asarray(anchors[1::2], jnp.float32)
    aw = aw_all[jnp.asarray(amask)]                        # [S]
    ah = ah_all[jnp.asarray(amask)]

    xf = x.astype(jnp.float32).reshape(N, S, 5 + Cn, H, W)
    tx, ty = xf[:, :, 0], xf[:, :, 1]                      # [N,S,H,W]
    tw, th = xf[:, :, 2], xf[:, :, 3]
    tobj = xf[:, :, 4]
    tcls = xf[:, :, 5:]                                    # [N,S,Cn,H,W]

    gb = gt_box.astype(jnp.float32)                        # [N,B,4] cx cy w h
    gl = gt_label.astype(jnp.int32)
    valid = gb[..., 2] > 0                                 # [N,B]
    gs = (gt_score.astype(jnp.float32) if gt_score is not None
          else jnp.ones((N, Bb), jnp.float32))

    # best anchor per gt over ALL anchors: IoU of origin-centered (w,h)
    gw_pix = gb[..., 2] * in_w                             # [N,B]
    gh_pix = gb[..., 3] * in_h
    inter = jnp.minimum(gw_pix[..., None], aw_all) * \
        jnp.minimum(gh_pix[..., None], ah_all)             # [N,B,A]
    union = gw_pix[..., None] * gh_pix[..., None] + \
        aw_all * ah_all - inter
    an_iou = inter / jnp.maximum(union, 1e-10)
    best = jnp.argmax(an_iou, axis=-1)                     # [N,B]
    # slot within this head (or -1 if the best anchor belongs elsewhere)
    slot = jnp.full((N, Bb), -1, jnp.int32)
    for si, a in enumerate(amask):
        slot = jnp.where(best == a, si, slot)
    assigned = valid & (slot >= 0)                         # [N,B]

    gi = jnp.clip((gb[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gb[..., 1] * H).astype(jnp.int32), 0, H - 1)
    n_idx = jnp.broadcast_to(jnp.arange(N)[:, None], (N, Bb))
    s_g = jnp.clip(slot, 0)

    # PER-GT accumulation (gather, not scatter): two gts sharing a cell
    # each contribute their own xy/wh/cls terms, exactly like the
    # reference kernel's per-box loop
    def sce(logit, target):
        # sigmoid cross entropy, numerically stable
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    wpos = jnp.where(assigned,
                     gs * (2.0 - gb[..., 2] * gb[..., 3]), 0.0)  # [N,B]
    txg = tx[n_idx, s_g, gj, gi]
    tyg = ty[n_idx, s_g, gj, gi]
    twg = tw[n_idx, s_g, gj, gi]
    thg = th[n_idx, s_g, gj, gi]
    loss_xy = (sce(txg, gb[..., 0] * W - gi)
               + sce(tyg, gb[..., 1] * H - gj)) * wpos
    loss_wh = (jnp.abs(twg - jnp.log(jnp.maximum(
        gw_pix / jnp.maximum(aw[s_g], 1e-10), 1e-10)))
        + jnp.abs(thg - jnp.log(jnp.maximum(
            gh_pix / jnp.maximum(ah[s_g], 1e-10), 1e-10)))) * wpos

    smooth_pos = 1.0 - 1.0 / Cn if (use_label_smooth and Cn > 1) else 1.0
    smooth_neg = 1.0 / Cn if (use_label_smooth and Cn > 1) else 0.0
    tclsg = tcls[n_idx[..., None], s_g[..., None],
                 jnp.arange(Cn)[None, None, :], gj[..., None],
                 gi[..., None]]                            # [N,B,Cn]
    cls_t = jnp.where(jnp.arange(Cn)[None, None] == gl[..., None],
                      smooth_pos, smooth_neg)
    loss_cls = jnp.sum(sce(tclsg, cls_t), axis=-1) \
        * jnp.where(assigned, gs, 0.0)

    # objectness target map (cell-level, set: a cell is positive once)
    s_idx = jnp.where(assigned, slot, S)   # OOB -> dropped by scatter

    def scat(vals):
        return jnp.zeros((N, S, H, W), jnp.float32) \
            .at[n_idx, s_idx, gj, gi].set(vals)

    obj_t = scat(jnp.ones((N, Bb), jnp.float32))
    score_t = scat(gs)

    # objectness: decode predictions, ignore where best IoU vs any gt
    # exceeds ignore_thresh (and the cell has no assigned gt)
    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    px = (jax.nn.sigmoid(tx) * scale_x_y - (scale_x_y - 1) / 2
          + grid_x) / W
    py = (jax.nn.sigmoid(ty) * scale_x_y - (scale_x_y - 1) / 2
          + grid_y) / H
    pw = jnp.exp(tw) * aw[None, :, None, None] / in_w
    ph = jnp.exp(th) * ah[None, :, None, None] / in_h

    def c2e(cx, cy, w, h):
        return cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2

    px1, py1, px2, py2 = c2e(px, py, pw, ph)               # [N,S,H,W]
    gx1, gy1, gx2, gy2 = c2e(gb[..., 0], gb[..., 1], gb[..., 2],
                             gb[..., 3])                   # [N,B]
    ew = jnp.maximum(
        jnp.minimum(px2[:, :, :, :, None], gx2[:, None, None, None])
        - jnp.maximum(px1[:, :, :, :, None], gx1[:, None, None, None]),
        0.0)
    eh = jnp.maximum(
        jnp.minimum(py2[:, :, :, :, None], gy2[:, None, None, None])
        - jnp.maximum(py1[:, :, :, :, None], gy1[:, None, None, None]),
        0.0)
    inter_p = ew * eh                                      # [N,S,H,W,B]
    area_p = (pw * ph)[:, :, :, :, None]
    area_g = (gb[..., 2] * gb[..., 3])[:, None, None, None]
    iou_p = inter_p / jnp.maximum(area_p + area_g - inter_p, 1e-10)
    iou_p = jnp.where(valid[:, None, None, None], iou_p, 0.0)
    ignore = (jnp.max(iou_p, axis=-1) > float(ignore_thresh)) \
        & (obj_t == 0)
    loss_obj = sce(tobj, obj_t) * jnp.where(
        obj_t > 0, score_t, jnp.where(ignore, 0.0, 1.0))

    per_img = jnp.sum(loss_xy + loss_wh + loss_cls, axis=1) \
        + jnp.sum(loss_obj, axis=(1, 2, 3))
    return per_img.astype(x.dtype)
