"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100 download-based loaders).

Zero-egress environment: the file-format parsers are kept (idx/ubyte for
MNIST, the CIFAR pickle batches) so local copies load exactly like the
reference, and ``FakeData`` provides deterministic synthetic images for
tests/benchmarks (the reference tests use the same trick).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples: int = 128, image_shape=(3, 32, 32),
                 num_classes: int = 10, transform: Optional[Callable] = None,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.images = rng.randint(
            0, 256, (num_samples,) + tuple(image_shape[1:])
            + (image_shape[0],), dtype=np.uint8)
        self.labels = rng.randint(0, num_classes,
                                  (num_samples,)).astype("int64")
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    """idx/ubyte-format MNIST (reference vision/datasets/mnist.py).

    ``image_path``/``label_path`` point at local (optionally .gz) idx
    files; no downloading in this environment.
    """

    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend=None):
        if image_path is None or label_path is None:
            raise ValueError(
                f"{type(self).__name__}: pass local image_path/label_path "
                "(idx/ubyte, optionally .gz) — downloading is disabled in "
                "this environment; use FakeData for synthetic runs")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        self.transform = transform

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else \
            open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class _CifarBase(Dataset):
    """CIFAR pickle-batch format (reference vision/datasets/cifar.py)."""

    _coarse = False

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend=None):
        if data_file is None:
            raise ValueError(
                f"{type(self).__name__}: pass a local data_file "
                "(cifar tar.gz or a batch pickle) — downloading is "
                "disabled; use FakeData for synthetic runs")
        images, labels = [], []
        label_key = self._label_key(mode)
        if data_file.endswith((".tar.gz", ".tgz", ".tar")):
            with tarfile.open(data_file) as tf:
                for m in tf.getmembers():
                    if self._want_member(m.name, mode):
                        d = pickle.load(tf.extractfile(m),
                                        encoding="bytes")
                        images.append(d[b"data"])
                        labels.extend(d[label_key])
        else:
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            images.append(d[b"data"])
            labels.extend(d[label_key])
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.images = data.transpose(0, 2, 3, 1)  # HWC like reference
        self.labels = np.asarray(labels, dtype="int64")
        self.transform = transform

    def _want_member(self, name, mode):
        base = os.path.basename(name)
        if mode == "train":
            return base.startswith("data_batch") or base == "train"
        return base.startswith("test_batch") or base == "test"

    def _label_key(self, mode):
        return b"coarse_labels" if self._coarse else (
            b"labels" if not self._coarse else b"labels")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    pass


class Cifar100(_CifarBase):
    def _label_key(self, mode):
        return b"fine_labels"
