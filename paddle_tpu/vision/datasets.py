"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100 download-based loaders).

Zero-egress environment: the file-format parsers are kept (idx/ubyte for
MNIST, the CIFAR pickle batches) so local copies load exactly like the
reference, and ``FakeData`` provides deterministic synthetic images for
tests/benchmarks (the reference tests use the same trick).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples: int = 128, image_shape=(3, 32, 32),
                 num_classes: int = 10, transform: Optional[Callable] = None,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.images = rng.randint(
            0, 256, (num_samples,) + tuple(image_shape[1:])
            + (image_shape[0],), dtype=np.uint8)
        self.labels = rng.randint(0, num_classes,
                                  (num_samples,)).astype("int64")
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    """idx/ubyte-format MNIST (reference vision/datasets/mnist.py).

    ``image_path``/``label_path`` point at local (optionally .gz) idx
    files; no downloading in this environment.
    """

    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend=None):
        if image_path is None or label_path is None:
            raise ValueError(
                f"{type(self).__name__}: pass local image_path/label_path "
                "(idx/ubyte, optionally .gz) — downloading is disabled in "
                "this environment; use FakeData for synthetic runs")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        self.transform = transform

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else \
            open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class _CifarBase(Dataset):
    """CIFAR pickle-batch format (reference vision/datasets/cifar.py)."""

    _coarse = False

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend=None):
        if data_file is None:
            raise ValueError(
                f"{type(self).__name__}: pass a local data_file "
                "(cifar tar.gz or a batch pickle) — downloading is "
                "disabled; use FakeData for synthetic runs")
        images, labels = [], []
        label_key = self._label_key(mode)
        if data_file.endswith((".tar.gz", ".tgz", ".tar")):
            with tarfile.open(data_file) as tf:
                for m in tf.getmembers():
                    if self._want_member(m.name, mode):
                        d = pickle.load(tf.extractfile(m),
                                        encoding="bytes")
                        images.append(d[b"data"])
                        labels.extend(d[label_key])
        else:
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            images.append(d[b"data"])
            labels.extend(d[label_key])
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.images = data.transpose(0, 2, 3, 1)  # HWC like reference
        self.labels = np.asarray(labels, dtype="int64")
        self.transform = transform

    def _want_member(self, name, mode):
        base = os.path.basename(name)
        if mode == "train":
            return base.startswith("data_batch") or base == "train"
        return base.startswith("test_batch") or base == "test"

    def _label_key(self, mode):
        return b"coarse_labels" if self._coarse else (
            b"labels" if not self._coarse else b"labels")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    pass


class Cifar100(_CifarBase):
    def _label_key(self, mode):
        return b"fine_labels"


def _decode_image(data, convert_rgb=True):
    """Decode encoded image bytes via Pillow (the one PIL chokepoint:
    label masks pass convert_rgb=False to keep palette indices)."""
    try:
        import io as _io

        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "image decoding needs Pillow (no nvjpeg analog on TPU "
            "hosts); .npy arrays load without it") from e
    img = Image.open(_io.BytesIO(data))
    return np.asarray(img.convert("RGB") if convert_rgb else img)


def _default_loader(path):
    """npy loads directly; encoded images via Pillow when present."""
    if path.endswith(".npy"):
        return np.load(path)
    with open(path, "rb") as f:
        return _decode_image(f.read())


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp", ".npy")


class DatasetFolder(Dataset):
    """Directory-per-class dataset (reference: vision/datasets/
    folder.py DatasetFolder): root/<class_name>/<file> discovered and
    mapped to contiguous class ids."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders found under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for base, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    p = os.path.join(base, fn)
                    ok = (is_valid_file(p) if is_valid_file
                          else fn.lower().endswith(exts))
                    if ok:
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"no files with extensions {exts} under {root!r}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive unlabeled image folder (reference: folder.py
    ImageFolder): every matching file, no labels."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        self.samples = []
        for base, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                p = os.path.join(base, fn)
                ok = (is_valid_file(p) if is_valid_file
                      else fn.lower().endswith(exts))
                if ok:
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(
                f"no files with extensions {exts} under {root!r}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 from LOCAL copies of the official files (reference:
    vision/datasets/flowers.py; zero-egress: pass data_file/label_file/
    setid_file paths; no downloading)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, backend=None):
        from ..core.enforce import enforce

        enforce(data_file and label_file and setid_file,
                "Flowers needs local data_file (102flowers.tgz), "
                "label_file (imagelabels.mat) and setid_file "
                "(setid.mat); this environment does not download")
        try:
            from scipy.io import loadmat
        except ImportError as e:  # pragma: no cover
            raise NotImplementedError(
                "Flowers label parsing needs scipy (.mat files)") from e
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = loadmat(setid_file)[key].ravel()
        self.labels = loadmat(label_file)["labels"].ravel()
        self.transform = transform
        self._tar = tarfile.open(data_file)
        self._names = {os.path.basename(n): n
                       for n in self._tar.getnames()
                       if n.endswith(".jpg")}

    def __getitem__(self, idx):
        flower_id = int(self.indexes[idx])
        name = f"image_{flower_id:05d}.jpg"
        data = self._tar.extractfile(self._names[name]).read()
        img = _decode_image(data)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[flower_id - 1] - 1)

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs from a LOCAL VOCtrainval tar
    (reference: vision/datasets/voc2012.py; zero-egress: pass
    data_file; no downloading)."""

    _BASE = "VOCdevkit/VOC2012"

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend=None):
        from ..core.enforce import enforce

        enforce(data_file, "VOC2012 needs a local data_file "
                           "(VOCtrainval tar); this environment does "
                           "not download")
        self._tar = tarfile.open(data_file)
        split = {"train": "train", "valid": "val", "test": "val",
                 "trainval": "trainval"}[mode]
        lst = self._tar.extractfile(
            f"{self._BASE}/ImageSets/Segmentation/{split}.txt")
        self.ids = [ln.strip() for ln in
                    lst.read().decode().splitlines() if ln.strip()]
        self.transform = transform

    def _img(self, path):
        # label masks keep their palette indices (convert_rgb=False)
        return _decode_image(self._tar.extractfile(path).read(),
                             convert_rgb=not path.endswith(".jpg"))

    def __getitem__(self, idx):
        name = self.ids[idx]
        img = self._img(f"{self._BASE}/JPEGImages/{name}.jpg")
        lab = self._img(f"{self._BASE}/SegmentationClass/{name}.png")
        if self.transform is not None:
            img = self.transform(img)
        return img, lab

    def __len__(self):
        return len(self.ids)
