"""Image transforms (reference: python/paddle/vision/transforms/
transforms.py + functional.py — Compose, Resize, Normalize, crops/flips,
ToTensor). Numpy/ndarray based (HWC uint8 in, like the reference's
'cv2'/'pil' backends); ToTensor produces CHW float Tensors.
"""
from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

from ..tensor import Tensor

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
    "RandomVerticalFlip", "Transpose", "Pad", "to_tensor", "normalize",
    "resize", "hflip", "vflip", "center_crop", "crop", "pad",
]


def _as_hwc(img) -> np.ndarray:
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def resize(img, size, interpolation="bilinear") -> np.ndarray:
    """Nearest/bilinear resize with pure numpy (no cv2/PIL dependency)."""
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        # shorter side → size, keep aspect (reference semantics)
        if h <= w:
            oh, ow = int(size), max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), int(size)
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return arr
    if interpolation == "nearest":
        ry = (np.arange(oh) * h / oh).astype(int).clip(0, h - 1)
        rx = (np.arange(ow) * w / ow).astype(int).clip(0, w - 1)
        return arr[ry][:, rx]
    # bilinear
    y = (np.arange(oh) + 0.5) * h / oh - 0.5
    x = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(y).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(x).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(y - y0, 0, 1)[:, None, None]
    wx = np.clip(x - x0, 0, 1)[None, :, None]
    a = arr.astype(np.float32)
    out = (a[y0][:, x0] * (1 - wy) * (1 - wx) + a[y1][:, x0] * wy * (1 - wx)
           + a[y0][:, x1] * (1 - wy) * wx + a[y1][:, x1] * wy * wx)
    return out.astype(arr.dtype) if np.issubdtype(arr.dtype, np.integer) \
        else out


def crop(img, top, left, height, width) -> np.ndarray:
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size) -> np.ndarray:
    arr = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    return crop(arr, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def hflip(img) -> np.ndarray:
    return _as_hwc(img)[:, ::-1]


def vflip(img) -> np.ndarray:
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant") -> np.ndarray:
    arr = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kw)


def to_tensor(img, data_format="CHW") -> Tensor:
    arr = _as_hwc(img).astype(np.float32)
    if arr.dtype == np.float32 and arr.max() > 1.5:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(__import__("jax.numpy", fromlist=["asarray"])
                  .asarray(arr))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        import jax.numpy as jnp

        mean_a = jnp.asarray(mean, jnp.float32)
        std_a = jnp.asarray(std, jnp.float32)
        shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
        return Tensor((img._value - mean_a.reshape(shape))
                      / std_a.reshape(shape))
    arr = np.asarray(img, np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (arr - np.reshape(mean, shape)) / np.reshape(std, shape)


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (int(size), int(size)) if isinstance(
            size, numbers.Number) else tuple(size)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _as_hwc(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        h, w = arr.shape[:2]
        th, tw = self.size
        top = random.randint(0, max(0, h - th))
        left = random.randint(0, max(0, w - tw))
        return crop(arr, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _as_hwc(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill = padding, fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)
