"""Image transforms (reference: python/paddle/vision/transforms/
transforms.py + functional.py — Compose, Resize, Normalize, crops/flips,
ToTensor). Numpy/ndarray based (HWC uint8 in, like the reference's
'cv2'/'pil' backends); ToTensor produces CHW float Tensors.
"""
from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

from ..tensor import Tensor

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
    "RandomVerticalFlip", "Transpose", "Pad", "to_tensor", "normalize",
    "resize", "hflip", "vflip", "center_crop", "crop", "pad",
    "ColorJitter", "RandomRotation", "rotate", "adjust_brightness",
    "adjust_contrast", "adjust_saturation", "adjust_hue",
]


def _as_hwc(img) -> np.ndarray:
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def resize(img, size, interpolation="bilinear") -> np.ndarray:
    """Nearest/bilinear resize with pure numpy (no cv2/PIL dependency)."""
    arr = _as_hwc(img)
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        # shorter side → size, keep aspect (reference semantics)
        if h <= w:
            oh, ow = int(size), max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), int(size)
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return arr
    if interpolation == "nearest":
        ry = (np.arange(oh) * h / oh).astype(int).clip(0, h - 1)
        rx = (np.arange(ow) * w / ow).astype(int).clip(0, w - 1)
        return arr[ry][:, rx]
    # bilinear
    y = (np.arange(oh) + 0.5) * h / oh - 0.5
    x = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(y).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(x).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(y - y0, 0, 1)[:, None, None]
    wx = np.clip(x - x0, 0, 1)[None, :, None]
    a = arr.astype(np.float32)
    out = (a[y0][:, x0] * (1 - wy) * (1 - wx) + a[y1][:, x0] * wy * (1 - wx)
           + a[y0][:, x1] * (1 - wy) * wx + a[y1][:, x1] * wy * wx)
    return out.astype(arr.dtype) if np.issubdtype(arr.dtype, np.integer) \
        else out


def crop(img, top, left, height, width) -> np.ndarray:
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size) -> np.ndarray:
    arr = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    return crop(arr, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def hflip(img) -> np.ndarray:
    return _as_hwc(img)[:, ::-1]


def vflip(img) -> np.ndarray:
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant") -> np.ndarray:
    arr = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kw)


def to_tensor(img, data_format="CHW") -> Tensor:
    arr = _as_hwc(img).astype(np.float32)
    if arr.dtype == np.float32 and arr.max() > 1.5:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(__import__("jax.numpy", fromlist=["asarray"])
                  .asarray(arr))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        import jax.numpy as jnp

        mean_a = jnp.asarray(mean, jnp.float32)
        std_a = jnp.asarray(std, jnp.float32)
        shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
        return Tensor((img._value - mean_a.reshape(shape))
                      / std_a.reshape(shape))
    arr = np.asarray(img, np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (arr - np.reshape(mean, shape)) / np.reshape(std, shape)


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (int(size), int(size)) if isinstance(
            size, numbers.Number) else tuple(size)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _as_hwc(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        h, w = arr.shape[:2]
        th, tw = self.size
        top = random.randint(0, max(0, h - th))
        left = random.randint(0, max(0, w - tw))
        return crop(arr, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _as_hwc(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill = padding, fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


# ---------------------------------------------------------------------------
# photometric / geometric functional ops (reference: vision/transforms/
# functional.py adjust_brightness:341, adjust_contrast:381,
# adjust_saturation:421, adjust_hue:462, rotate:720)
# ---------------------------------------------------------------------------
def adjust_brightness(img, brightness_factor: float) -> np.ndarray:
    arr = _as_hwc(img).astype(np.float32)
    hi = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    out = np.clip(arr * float(brightness_factor), 0, hi)
    return out.astype(np.asarray(img).dtype)


def adjust_contrast(img, contrast_factor: float) -> np.ndarray:
    arr = _as_hwc(img).astype(np.float32)
    hi = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    # reference blends toward the mean of the grayscale image
    gray = arr @ np.asarray([0.299, 0.587, 0.114], np.float32) \
        if arr.shape[-1] == 3 else arr[..., 0]
    mean = float(gray.mean())
    out = np.clip(mean + float(contrast_factor) * (arr - mean), 0, hi)
    return out.astype(np.asarray(img).dtype)


def adjust_saturation(img, saturation_factor: float) -> np.ndarray:
    arr = _as_hwc(img).astype(np.float32)
    hi = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    if arr.shape[-1] != 3:
        return _as_hwc(img)
    gray = (arr @ np.asarray([0.299, 0.587, 0.114],
                             np.float32))[..., None]
    out = np.clip(gray + float(saturation_factor) * (arr - gray), 0, hi)
    return out.astype(np.asarray(img).dtype)


def adjust_hue(img, hue_factor: float) -> np.ndarray:
    """hue_factor in [-0.5, 0.5]: shift the H channel in HSV space."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} not in [-0.5, 0.5]")
    src = np.asarray(img)
    arr = _as_hwc(img).astype(np.float32)
    if arr.shape[-1] != 3:
        return _as_hwc(img)
    hi = 255.0 if src.dtype == np.uint8 else 1.0
    arr = arr / hi
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    mx = arr.max(-1)
    mn = arr.min(-1)
    d = mx - mn + 1e-12
    h = np.zeros_like(mx)
    sel = mx == r
    h[sel] = (((g - b) / d) % 6)[sel]
    sel = mx == g
    h[sel] = ((b - r) / d + 2)[sel]
    sel = mx == b
    h[sel] = ((r - g) / d + 4)[sel]
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, d / (mx + 1e-12), 0)
    v = mx
    # hsv -> rgb
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = (i.astype(np.int32) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return (np.clip(out, 0, 1) * hi).astype(src.dtype)


def rotate(img, angle: float, interpolation="nearest", expand=False,
           center=None, fill=0) -> np.ndarray:
    """Rotate counter-clockwise by ``angle`` degrees (inverse affine
    map + nearest/bilinear sampling, the reference's cv2/PIL path)."""
    arr = _as_hwc(img).astype(np.float32)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    a = np.deg2rad(angle)
    cos_a, sin_a = np.cos(a), np.sin(a)
    if expand:
        # epsilon guard: sin(pi/2) etc. leave ~1e-16 dust that would
        # ceil a 3.0-wide canvas up to 4
        nw = int(np.ceil(abs(w * cos_a) + abs(h * sin_a) - 1e-9))
        nh = int(np.ceil(abs(w * sin_a) + abs(h * cos_a) - 1e-9))
    else:
        nh, nw = h, w
    yy, xx = np.meshgrid(np.arange(nh, dtype=np.float32),
                         np.arange(nw, dtype=np.float32), indexing="ij")
    ocy, ocx = (nh - 1) / 2.0, (nw - 1) / 2.0
    # inverse rotation: output pixel -> source location (PIL/reference
    # convention: positive angle = counter-clockwise on screen, which
    # in y-down pixel coordinates is the clockwise matrix)
    sx = cos_a * (xx - ocx) - sin_a * (yy - ocy) + cx
    sy = sin_a * (xx - ocx) + cos_a * (yy - ocy) + cy
    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = sx - x0
        wy = sy - y0
        out = np.zeros((nh, nw, arr.shape[2]), np.float32)
        for dy in (0, 1):
            for dx in (0, 1):
                xi = np.clip(x0 + dx, 0, w - 1)
                yi = np.clip(y0 + dy, 0, h - 1)
                wgt = (wx if dx else 1 - wx) * (wy if dy else 1 - wy)
                out += arr[yi, xi] * wgt[..., None]
        inside = (sx >= -0.5) & (sx <= w - 0.5) & (sy >= -0.5) \
            & (sy <= h - 0.5)
    else:
        xi = np.clip(np.round(sx).astype(np.int64), 0, w - 1)
        yi = np.clip(np.round(sy).astype(np.int64), 0, h - 1)
        out = arr[yi, xi]
        inside = (np.round(sx) >= 0) & (np.round(sx) <= w - 1) \
            & (np.round(sy) >= 0) & (np.round(sy) <= h - 1)
    out = np.where(inside[..., None], out, np.float32(fill))
    return out.astype(np.asarray(img).dtype)


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (reference: vision/transforms/transforms.py ColorJitter)."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0, keys=None):
        def rng(v, name, center=1.0, lo=0.0):
            if isinstance(v, numbers.Number):
                if v < 0:
                    raise ValueError(f"{name} must be non-negative, "
                                     f"got {v}")
                v = [max(center - v, lo), center + v] if v else None
            if v is not None:
                v = tuple(v)
                if not lo - 1e-9 <= v[0] <= v[1]:
                    raise ValueError(f"{name} range {v} invalid "
                                     f"(need {lo} <= lo <= hi)")
            return v

        self.brightness = rng(brightness, "brightness")
        self.contrast = rng(contrast, "contrast")
        self.saturation = rng(saturation, "saturation")
        self.hue = rng(hue, "hue", center=0.0, lo=-0.5)
        if self.hue and self.hue[1] > 0.5:
            raise ValueError(f"hue range {self.hue} exceeds [-0.5, 0.5]")

    def _apply_image(self, img):
        ops = []
        for bounds, fn in ((self.brightness, adjust_brightness),
                           (self.contrast, adjust_contrast),
                           (self.saturation, adjust_saturation),
                           (self.hue, adjust_hue)):
            if bounds:
                # default-arg binding: each op keeps ITS OWN factor
                ops.append(lambda im, f=random.uniform(*bounds),
                           fn=fn: fn(im, f))
        random.shuffle(ops)
        out = _as_hwc(img)
        for op in ops:
            out = op(out)
        return out


class RandomRotation(BaseTransform):
    """Rotate by a random angle from ``degrees`` (reference:
    transforms.py RandomRotation)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)
