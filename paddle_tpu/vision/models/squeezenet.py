"""SqueezeNet v1.0/1.1 (reference: python/paddle/vision/models/
squeezenet.py — same factory surface).
"""
from __future__ import annotations

from ... import concat, nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, expand1, expand3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, expand1, 1)
        self.expand3 = nn.Conv2D(squeeze, expand3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1(x)),
                       self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(512, 64, 256, 256),
            )
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, padding=1), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            )
        if num_classes > 0:
            self.classifier_drop = nn.Dropout(0.5)
            self.classifier_conv = nn.Conv2D(512, num_classes, 1)
            self.classifier_relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier_relu(
                self.classifier_conv(self.classifier_drop(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet(version="1.1", **kwargs)
