from .lenet import LeNet  # noqa: F401
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152)  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "LeNet", "VGG", "vgg11", "vgg13", "vgg16",
           "vgg19", "MobileNetV2", "mobilenet_v2"]
