from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152)  # noqa: F401

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152"]
