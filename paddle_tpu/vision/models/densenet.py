"""DenseNet (reference: python/paddle/vision/models/densenet.py — same
factory surface; dense blocks with bottleneck layers + transitions).
"""
from __future__ import annotations

from ... import concat, nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        assert layers in _CFG, f"supported layers: {sorted(_CFG)}"
        num_init, growth, block_cfg = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(num_init)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)

        blocks = []
        ch = num_init
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch = ch // 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_last = nn.BatchNorm2D(ch)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.relu(self.bn_last(self.blocks(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _densenet(layers, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, **kwargs)
