"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py —
same factory surface; standard InceptionA-E topology, 299x299 input).
"""
from __future__ import annotations

from ... import concat, nn

__all__ = ["InceptionV3", "inception_v3"]


class _ConvBNRelu(nn.Layer):
    def __init__(self, in_ch, out_ch, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, in_ch, pool_features):
        super().__init__()
        self.b1 = _ConvBNRelu(in_ch, 64, 1)
        self.b5_1 = _ConvBNRelu(in_ch, 48, 1)
        self.b5_2 = _ConvBNRelu(48, 64, 5, padding=2)
        self.b3_1 = _ConvBNRelu(in_ch, 64, 1)
        self.b3_2 = _ConvBNRelu(64, 96, 3, padding=1)
        self.b3_3 = _ConvBNRelu(96, 96, 3, padding=1)
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBNRelu(in_ch, pool_features, 1)

    def forward(self, x):
        return concat([self.b1(x), self.b5_2(self.b5_1(x)),
                       self.b3_3(self.b3_2(self.b3_1(x))),
                       self.bp(self.pool(x))], axis=1)


class _InceptionB(nn.Layer):
    """Grid reduction 35x35 -> 17x17."""

    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _ConvBNRelu(in_ch, 384, 3, stride=2)
        self.bd_1 = _ConvBNRelu(in_ch, 64, 1)
        self.bd_2 = _ConvBNRelu(64, 96, 3, padding=1)
        self.bd_3 = _ConvBNRelu(96, 96, 3, stride=2)
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.bd_3(self.bd_2(self.bd_1(x))),
                       self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_ch, ch7):
        super().__init__()
        self.b1 = _ConvBNRelu(in_ch, 192, 1)
        self.b7_1 = _ConvBNRelu(in_ch, ch7, 1)
        self.b7_2 = _ConvBNRelu(ch7, ch7, (1, 7), padding=(0, 3))
        self.b7_3 = _ConvBNRelu(ch7, 192, (7, 1), padding=(3, 0))
        self.b7d_1 = _ConvBNRelu(in_ch, ch7, 1)
        self.b7d_2 = _ConvBNRelu(ch7, ch7, (7, 1), padding=(3, 0))
        self.b7d_3 = _ConvBNRelu(ch7, ch7, (1, 7), padding=(0, 3))
        self.b7d_4 = _ConvBNRelu(ch7, ch7, (7, 1), padding=(3, 0))
        self.b7d_5 = _ConvBNRelu(ch7, 192, (1, 7), padding=(0, 3))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBNRelu(in_ch, 192, 1)

    def forward(self, x):
        b7 = self.b7_3(self.b7_2(self.b7_1(x)))
        b7d = self.b7d_5(self.b7d_4(self.b7d_3(self.b7d_2(self.b7d_1(x)))))
        return concat([self.b1(x), b7, b7d, self.bp(self.pool(x))], axis=1)


class _InceptionD(nn.Layer):
    """Grid reduction 17x17 -> 8x8."""

    def __init__(self, in_ch):
        super().__init__()
        self.b3_1 = _ConvBNRelu(in_ch, 192, 1)
        self.b3_2 = _ConvBNRelu(192, 320, 3, stride=2)
        self.b7_1 = _ConvBNRelu(in_ch, 192, 1)
        self.b7_2 = _ConvBNRelu(192, 192, (1, 7), padding=(0, 3))
        self.b7_3 = _ConvBNRelu(192, 192, (7, 1), padding=(3, 0))
        self.b7_4 = _ConvBNRelu(192, 192, 3, stride=2)
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3_2(self.b3_1(x)),
                       self.b7_4(self.b7_3(self.b7_2(self.b7_1(x)))),
                       self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _ConvBNRelu(in_ch, 320, 1)
        self.b3_1 = _ConvBNRelu(in_ch, 384, 1)
        self.b3_2a = _ConvBNRelu(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _ConvBNRelu(384, 384, (3, 1), padding=(1, 0))
        self.b3d_1 = _ConvBNRelu(in_ch, 448, 1)
        self.b3d_2 = _ConvBNRelu(448, 384, 3, padding=1)
        self.b3d_3a = _ConvBNRelu(384, 384, (1, 3), padding=(0, 1))
        self.b3d_3b = _ConvBNRelu(384, 384, (3, 1), padding=(1, 0))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBNRelu(in_ch, 192, 1)

    def forward(self, x):
        b3 = self.b3_1(x)
        b3 = concat([self.b3_2a(b3), self.b3_2b(b3)], axis=1)
        b3d = self.b3d_2(self.b3d_1(x))
        b3d = concat([self.b3d_3a(b3d), self.b3d_3b(b3d)], axis=1)
        return concat([self.b1(x), b3, b3d, self.bp(self.pool(x))], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBNRelu(3, 32, 3, stride=2),
            _ConvBNRelu(32, 32, 3),
            _ConvBNRelu(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBNRelu(64, 80, 1),
            _ConvBNRelu(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x).flatten(1))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
