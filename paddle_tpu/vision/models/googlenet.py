"""GoogLeNet / Inception v1 (reference: python/paddle/vision/models/
googlenet.py — same factory surface and (out, out1, out2) aux-head
forward contract).
"""
from __future__ import annotations

from ... import concat, nn

__all__ = ["GoogLeNet", "googlenet"]


class _ConvRelu(nn.Layer):
    def __init__(self, in_ch, out_ch, k, stride=1):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=(k - 1) // 2, bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.conv(x))


class _Inception(nn.Layer):
    def __init__(self, in_ch, f1, f3r, f3, f5r, f5, proj):
        super().__init__()
        self.b1 = _ConvRelu(in_ch, f1, 1)
        self.b3r = _ConvRelu(in_ch, f3r, 1)
        self.b3 = _ConvRelu(f3r, f3, 3)
        self.b5r = _ConvRelu(in_ch, f5r, 1)
        self.b5 = _ConvRelu(f5r, f5, 5)
        self.pool = nn.MaxPool2D(3, stride=1, padding=1)
        self.proj = _ConvRelu(in_ch, proj, 1)

    def forward(self, x):
        return concat([self.b1(x), self.b3(self.b3r(x)),
                       self.b5(self.b5r(x)), self.proj(self.pool(x))],
                      axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv = _ConvRelu(3, 64, 7, stride=2)
        self.pool = nn.MaxPool2D(3, stride=2)
        self.conv_1 = _ConvRelu(64, 64, 1)
        self.conv_2 = _ConvRelu(64, 192, 3)

        self.ince3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.ince4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.ince5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = _Inception(832, 384, 192, 384, 48, 128, 128)

        if with_pool:
            self.pool_5 = nn.AdaptiveAvgPool2D(1)
            self.pool_o1 = nn.AvgPool2D(5, stride=3)
            self.pool_o2 = nn.AvgPool2D(5, stride=3)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc_out = nn.Linear(1024, num_classes)
            self.conv_o1 = _ConvRelu(512, 128, 1)
            self.fc_o1 = nn.Linear(1152, 1024)
            self.drop_o1 = nn.Dropout(0.7)
            self.out1 = nn.Linear(1024, num_classes)
            self.conv_o2 = _ConvRelu(528, 128, 1)
            self.fc_o2 = nn.Linear(1152, 1024)
            self.drop_o2 = nn.Dropout(0.7)
            self.out2 = nn.Linear(1024, num_classes)
            self.relu = nn.ReLU()

    def forward(self, x):
        x = self.pool(self.conv(x))
        x = self.pool(self.conv_2(self.conv_1(x)))
        x = self.pool(self.ince3b(self.ince3a(x)))
        ince4a = self.ince4a(x)
        x = self.ince4c(self.ince4b(ince4a))
        ince4d = self.ince4d(x)
        x = self.pool(self.ince4e(ince4d))
        out = self.ince5b(self.ince5a(x))
        out1, out2 = ince4a, ince4d

        if self.with_pool:
            out = self.pool_5(out)
            out1 = self.pool_o1(out1)
            out2 = self.pool_o2(out2)
        if self.num_classes > 0:
            out = self.fc_out(self.drop(out).flatten(1))
            out1 = self.relu(self.fc_o1(self.conv_o1(out1).flatten(1)))
            out1 = self.out1(self.drop_o1(out1))
            out2 = self.relu(self.fc_o2(self.conv_o2(out2).flatten(1)))
            out2 = self.out2(self.drop_o2(out2))
        return out, out1, out2


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
