"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py
— same factory surface). Channel shuffle is a reshape/transpose pair,
which XLA folds into the surrounding convs' layouts.
"""
from __future__ import annotations

from ... import concat, nn, reshape, transpose

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


def _channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class _ConvBNAct(nn.Layer):
    def __init__(self, in_ch, out_ch, k, stride=1, groups=1, act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.act = _act(act) if act else nn.Identity()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _InvertedResidual(nn.Layer):
    """Stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, ch, act):
        super().__init__()
        half = ch // 2
        self.branch = nn.Sequential(
            _ConvBNAct(half, half, 1, act=act),
            _ConvBNAct(half, half, 3, groups=half, act=None),
            _ConvBNAct(half, half, 1, act=act),
        )

    def forward(self, x):
        half = x.shape[1] // 2
        x1, x2 = x[:, :half], x[:, half:]
        out = concat([x1, self.branch(x2)], axis=1)
        return _channel_shuffle(out, 2)


class _InvertedResidualDS(nn.Layer):
    """Stride-2 (downsample) unit: both branches transform."""

    def __init__(self, in_ch, out_ch, act):
        super().__init__()
        half = out_ch // 2
        self.branch1 = nn.Sequential(
            _ConvBNAct(in_ch, in_ch, 3, stride=2, groups=in_ch, act=None),
            _ConvBNAct(in_ch, half, 1, act=act),
        )
        self.branch2 = nn.Sequential(
            _ConvBNAct(in_ch, half, 1, act=act),
            _ConvBNAct(half, half, 3, stride=2, groups=half, act=None),
            _ConvBNAct(half, half, 1, act=act),
        )

    def forward(self, x):
        out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        channels = {
            0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
            0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
            1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
        }[scale]
        self.conv1 = _ConvBNAct(3, channels[0], 3, stride=2, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        in_ch = channels[0]
        for stage, repeats in enumerate(stage_repeats):
            out_ch = channels[stage + 1]
            blocks.append(_InvertedResidualDS(in_ch, out_ch, act))
            for _ in range(repeats - 1):
                blocks.append(_InvertedResidual(out_ch, act))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = _ConvBNAct(in_ch, channels[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.blocks(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
