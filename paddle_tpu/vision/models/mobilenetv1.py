"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py —
same factory surface; depthwise-separable conv stacks).
"""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class _ConvBNRelu(nn.Layer):
    def __init__(self, in_ch, out_ch, k, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _DepthwiseSep(nn.Layer):
    def __init__(self, in_ch, out1, out2, stride, scale):
        super().__init__()
        in_ch = int(in_ch * scale)
        self.dw = _ConvBNRelu(in_ch, int(out1 * scale), 3, stride=stride,
                              padding=1, groups=in_ch)
        self.pw = _ConvBNRelu(int(out1 * scale), int(out2 * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = _ConvBNRelu(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [  # (in, out_dw, out_pw, stride)
            (32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
            (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
            (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
            (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
            (1024, 1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(
            *[_DepthwiseSep(i, o1, o2, s, scale) for i, o1, o2, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
