"""MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py —
inverted residuals with depthwise convs)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel_size=3, stride=1, groups=1):
        padding = (kernel_size - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel_size, stride=stride,
                      padding=padding, groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6())


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res_connect = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden, kernel_size=1))
        layers.extend([
            ConvBNReLU(hidden, hidden, stride=stride, groups=hidden),
            nn.Conv2D(hidden, oup, 1, stride=1, padding=0,
                      bias_attr=False),
            nn.BatchNorm2D(oup),
        ])
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        if self.use_res_connect:
            return x + self.conv(x)
        return self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = 32
        last_channel = 1280
        cfg = [
            # t, c, n, s
            [1, 16, 1, 1], [6, 24, 2, 2], [6, 32, 3, 2], [6, 64, 4, 2],
            [6, 96, 3, 1], [6, 160, 3, 2], [6, 320, 1, 1],
        ]
        input_channel = _make_divisible(input_channel * scale)
        self.last_channel = _make_divisible(last_channel * max(1.0, scale))
        features = [ConvBNReLU(3, input_channel, stride=2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        features.append(ConvBNReLU(input_channel, self.last_channel,
                                   kernel_size=1))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            from ...ops import manipulation as M

            x = M.flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
