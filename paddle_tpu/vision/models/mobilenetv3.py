"""MobileNetV3 small/large (reference: python/paddle/vision/models/
mobilenetv3.py — same factory surface; inverted residuals with
squeeze-excitation and hardswish).
"""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNAct(nn.Layer):
    def __init__(self, in_ch, out_ch, k, stride=1, groups=1, act=None):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.act = {"relu": nn.ReLU, "hardswish": nn.Hardswish,
                    None: nn.Identity}[act]()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _SqueezeExcitation(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, exp_ch, out_ch, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if exp_ch != in_ch:
            layers.append(_ConvBNAct(in_ch, exp_ch, 1, act=act))
        layers.append(_ConvBNAct(exp_ch, exp_ch, k, stride=stride,
                                 groups=exp_ch, act=act))
        if use_se:
            layers.append(
                _SqueezeExcitation(exp_ch, _make_divisible(exp_ch // 4)))
        layers.append(_ConvBNAct(exp_ch, out_ch, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    # cfg rows: (k, exp, out, use_se, act, stride)
    def __init__(self, cfg, last_ch, scale, num_classes, with_pool):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_ch = _make_divisible(16 * scale)
        self.conv = _ConvBNAct(3, in_ch, 3, stride=2, act="hardswish")
        blocks = []
        for k, exp, out, use_se, act, stride in cfg:
            exp_ch = _make_divisible(exp * scale)
            out_ch = _make_divisible(out * scale)
            blocks.append(_InvertedResidual(in_ch, exp_ch, out_ch, k,
                                            stride, use_se, act))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        last_conv = _make_divisible(6 * in_ch)
        self.lastconv = _ConvBNAct(in_ch, last_conv, 1, act="hardswish")
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.lastconv(self.blocks(self.conv(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [
            (3, 16, 16, True, "relu", 2),
            (3, 72, 24, False, "relu", 2),
            (3, 88, 24, False, "relu", 1),
            (5, 96, 40, True, "hardswish", 2),
            (5, 240, 40, True, "hardswish", 1),
            (5, 240, 40, True, "hardswish", 1),
            (5, 120, 48, True, "hardswish", 1),
            (5, 144, 48, True, "hardswish", 1),
            (5, 288, 96, True, "hardswish", 2),
            (5, 576, 96, True, "hardswish", 1),
            (5, 576, 96, True, "hardswish", 1),
        ]
        super().__init__(cfg, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [
            (3, 16, 16, False, "relu", 1),
            (3, 64, 24, False, "relu", 2),
            (3, 72, 24, False, "relu", 1),
            (5, 72, 40, True, "relu", 2),
            (5, 120, 40, True, "relu", 1),
            (5, 120, 40, True, "relu", 1),
            (3, 240, 80, False, "hardswish", 2),
            (3, 200, 80, False, "hardswish", 1),
            (3, 184, 80, False, "hardswish", 1),
            (3, 184, 80, False, "hardswish", 1),
            (3, 480, 112, True, "hardswish", 1),
            (3, 672, 112, True, "hardswish", 1),
            (5, 672, 160, True, "hardswish", 2),
            (5, 960, 160, True, "hardswish", 1),
            (5, 960, 160, True, "hardswish", 1),
        ]
        super().__init__(cfg, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
