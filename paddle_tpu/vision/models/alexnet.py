"""AlexNet (reference: python/paddle/vision/models/alexnet.py — same
factory surface; implementation is the standard 5-conv/3-fc topology).
"""
from __future__ import annotations

from ... import nn

__all__ = ["AlexNet", "alexnet"]


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2),
            nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2),
            nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1),
            nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1),
            nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
        )
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Linear(256 * 6 * 6, 4096),
                nn.ReLU(),
                nn.Dropout(0.5),
                nn.Linear(4096, 4096),
                nn.ReLU(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)
