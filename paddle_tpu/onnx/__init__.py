"""ONNX export (reference: python/paddle/onnx/export.py, which defers
to the external paddle2onnx package). Exporting an XLA-compiled model
to ONNX requires an ONNX runtime/converter dependency this environment
does not ship, so the API is present but gated; jit.save provides the
native serialization path (StableHLO via jax.export is the TPU-world
interchange format).
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export requires the external paddle2onnx/onnx toolchain, "
        "which is not available in this build. Use paddle_tpu.jit.save "
        "for native serialization (jax.export StableHLO).")
