"""Multi-process bootstrap — MUST run before any XLA backend touch.

``jax.distributed.initialize`` has to be called before the first
``jax.devices()``/computation, but importing the framework already
touches the backend (op registration, dtype tables). So the very first
statement of ``paddle_tpu/__init__`` calls :func:`bootstrap`, which joins
the global jax runtime when the launcher envs say this is a ranked
process of a pod (reference analog: parallel.py:943 init_parallel_env's
store+ProcessGroup bootstrap, which Paddle likewise triggers before any
collective).

Kept dependency-free (no other paddle_tpu imports) so it can run first.
The jax coordination service address is ``PADDLE_MASTER`` host with
port+1 — the TCPStore owns the master port itself — or the explicit
``JAX_COORDINATOR_ADDRESS`` override set by the launcher.
"""
from __future__ import annotations

import os

_done = False


def bootstrap() -> None:
    global _done
    if _done:
        return
    _done = True
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if world <= 1:
        return
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coord:
        master = os.environ.get("PADDLE_MASTER", "")
        if not master:
            return  # no rendezvous info — stay single-process
        host, _, port = master.partition(":")
        coord = f"{host or '127.0.0.1'}:{int(port or 0) + 1}"
    import jax

    try:
        # XLA:CPU cross-process collectives ride gloo (the reference's
        # process_group_gloo.cc role); harmless on TPU backends.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=world, process_id=rank)
