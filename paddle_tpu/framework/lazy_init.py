"""Lazy (deferred) parameter initialization — paddle.LazyGuard analog.

(reference: python/paddle/nn/initializer/lazy_init.py ``LazyGuard`` —
there it defers initializer *ops* into a startup program so a huge model
can be constructed without storage. TPU-native redesign: a Parameter
built under ``LazyGuard`` carries only a :class:`LazySpec` (shape,
dtype, initializer); ``ParallelEngine`` / ``materialize_lazy_params``
later materializes each parameter DIRECTLY AT ITS SHARDING — every
process generates only its addressable shard windows via the keyed
window generation in nn/initializer.py, so host+device footprint is
O(shard), never O(model). This replaces the reference's
rank-0-init-then-broadcast (fleet/utils/hybrid_parallel_util.py:213)
with zero-communication deterministic shard init.)

Usage::

    with paddle.LazyGuard():
        model = GPTForCausalLM(gpt_13b())       # no storage allocated
    eng = ParallelEngine(model, opt, hcg.mesh)  # materializes sharded
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from ..core.dtype import convert_dtype

__all__ = ["LazyGuard", "LazySpec", "in_lazy_mode"]

_state = threading.local()


def in_lazy_mode() -> bool:
    return getattr(_state, "lazy", False)


class LazyGuard:
    def __enter__(self):
        self._prev = in_lazy_mode()
        _state.lazy = True
        return self

    def __exit__(self, *exc):
        _state.lazy = self._prev
        return False


class LazySpec:
    """Stands in for a Parameter's backing array until materialization.

    Exposes shape/dtype/ndim/size (so dist_attr plumbing and
    ``Layer.to(dtype=...)`` work unchanged — ``astype`` returns a
    re-dtyped LazySpec); any attempt to read VALUES before
    materialization raises with a pointer to the fix.
    """

    __slots__ = ("shape", "dtype", "init")

    def __init__(self, shape, dtype, init):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = jnp.dtype(convert_dtype(dtype))
        self.init = init

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def astype(self, dtype):
        return LazySpec(self.shape, dtype, self.init)

    def __repr__(self):
        return (f"LazySpec(shape={self.shape}, dtype={self.dtype}, "
                f"init={type(self.init).__name__})")

    def _no_value(self, what):
        raise RuntimeError(
            f"cannot {what} a lazy parameter: it was created under "
            "paddle.LazyGuard and has no storage yet. Materialize it "
            "first (ParallelEngine(...) does this automatically, or call "
            "paddle_tpu.distributed.engine.materialize_lazy_params).")

    def __array__(self, *a, **k):
        self._no_value("read")

    def __jax_array__(self):
        self._no_value("read")

    def __getitem__(self, idx):
        self._no_value("index")

    def __add__(self, other):
        self._no_value("compute with")

    __radd__ = __mul__ = __rmul__ = __sub__ = __matmul__ = __add__
