"""Framework utilities: ParamAttr, save/load, random seeds.

(reference: python/paddle/framework/*)
"""
from . import io  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .selected_rows import SelectedRows, merge_selected_rows  # noqa: F401
from ..core.rng import seed  # noqa: F401


def get_default_dtype():
    from ..core.dtype import get_default_dtype as g

    return g()


def set_default_dtype(d):
    from ..core.dtype import set_default_dtype as s

    return s(d)
