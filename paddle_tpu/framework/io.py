"""paddle.save / paddle.load analog.

(reference: python/paddle/framework/io.py:721,960 — pickle-protocol
serialization of state_dicts with tensors converted to ndarray.)
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..tensor import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


def _to_serializable(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return {"__paddle_tpu_tensor__": True,
                "data": np.asarray(obj._value),
                "stop_gradient": obj.stop_gradient,
                "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj: Any, return_numpy: bool = False) -> Any:
    if isinstance(obj, dict):
        if obj.get("__paddle_tpu_tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(__import__("jax.numpy", fromlist=["asarray"]).asarray(
                obj["data"]), stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name", "")
            return t
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_serializable(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **configs) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_serializable(obj, return_numpy=return_numpy)
