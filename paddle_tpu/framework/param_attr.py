"""ParamAttr (paddle.ParamAttr analog).

(reference: python/paddle/base/param_attr.py — bundles name/initializer/
learning_rate/regularizer/trainable for create_parameter.)
"""
from __future__ import annotations

from typing import Optional

__all__ = ["ParamAttr"]


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, do_model_average: bool = True,
                 need_clip: bool = True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None or isinstance(arg, ParamAttr) or arg is False:
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        # assume initializer
        return ParamAttr(initializer=arg)
