"""SelectedRows: a row-sparse gradient (reference:
paddle/phi/core/selected_rows.h:32 — rows_ + value_ + height_; produced
by the sparse embedding-gradient kernel
phi/kernels/cpu|gpu/embedding_sparse_grad_kernel.cc and consumed by the
optimizers' sparse update kernels, e.g. adam's lazy_mode row updates).

TPU redesign: the value is a dense [n_rows, dim...] jax array + an int32
row-id vector — the pair stays on device and flows through the autograd
engine as a leaf gradient; the optimizer applies it as an XLA scatter
over only the touched rows (plus lazy per-row moment updates for Adam),
so a step on a small batch never materializes (vocab, dim) gradients.
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

__all__ = ["SelectedRows", "merge_selected_rows"]


class SelectedRows:
    """rows: int32 [n]; values: [n, ...]; height: size of dim 0 of the
    dense equivalent. Duplicate row ids are allowed (accumulated on
    merge/to_dense, matching the reference's MergeAdd semantics)."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = jnp.asarray(rows, jnp.int32).reshape(-1)
        self.values = jnp.asarray(values)
        self.height = int(height)

    @property
    def shape(self):
        return [self.height] + list(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense_value(self) -> jax.Array:
        out = jnp.zeros((self.height,) + self.values.shape[1:],
                        self.values.dtype)
        return out.at[self.rows].add(self.values)

    @property
    def _value(self) -> jax.Array:
        """Dense view for generic Tensor-shaped consumers (grad clip,
        user inspection). The optimizer checks isinstance(...,
        SelectedRows) FIRST and never takes this densifying path."""
        return self.to_dense_value()

    def numpy(self):
        return np.asarray(self.to_dense_value())

    def is_selected_rows(self) -> bool:
        return True

    def merge(self) -> "SelectedRows":
        return merge_selected_rows(self)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"n_rows={self.values.shape[0]}, "
                f"value_shape={list(self.values.shape[1:])})")


def merge_selected_rows(sr: SelectedRows) -> SelectedRows:
    """Accumulate duplicate row ids (reference: merge_selected_rows op /
    MergeAdd functor). Static-shaped: the output keeps n slots with
    unique ids first (segment-sum by first-occurrence index); the freed
    duplicate slots get row id = height, which is OUT OF BOUNDS: XLA
    drops out-of-bounds scatter updates, so those slots are inert for
    every scatter consumer without any dynamic shaping; the zero value
    keeps any gather-based consumer harmless too."""
    rows = np.asarray(sr.rows)
    uniq, inv = np.unique(rows, return_inverse=True)
    n = sr.values.shape[0]
    seg = jnp.zeros((n,) + sr.values.shape[1:], sr.values.dtype)
    seg = seg.at[jnp.asarray(inv)].add(sr.values)
    out_rows = np.full(n, sr.height, np.int32)
    out_rows[:len(uniq)] = uniq
    return SelectedRows(out_rows, seg, sr.height)
