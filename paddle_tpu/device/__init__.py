"""Device management (reference: python/paddle/device/__init__.py:191
set_device incl. custom devices; CUDA streams API).

On TPU, XLA/PJRT owns streams and memory; this module exposes the same
query surface over jax.devices().
"""
from __future__ import annotations

from typing import List

import jax

__all__ = ["set_device", "get_device", "get_all_devices", "device_count",
           "is_compiled_with_cuda", "synchronize", "Stream", "Event",
           "current_stream"]

_current = None


def set_device(device: str):
    """Accepts "tpu", "tpu:N", "cpu" — device placement is owned by XLA;
    this records the preference used by to_tensor placement."""
    global _current
    _current = device
    return device


def get_device() -> str:
    if _current is not None:
        return _current
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def get_all_devices() -> List[str]:
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def synchronize(device=None):
    """Block until all queued work completes (effectful_barrier analog)."""
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()


class Stream:
    """API-parity stub: XLA owns stream scheduling on TPU; kept so code
    written against paddle.device.Stream imports (reference:
    python/paddle/device/__init__.py Stream)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False):
        pass

    def record(self, stream=None):
        pass

    def synchronize(self):
        synchronize()

    def query(self) -> bool:
        return True


def current_stream(device=None) -> Stream:
    return Stream(device)
