"""Incubate optimizers (reference: python/paddle/incubate/optimizer/
lookahead.py, modelaverage.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..optimizer import Optimizer


class LookAhead(Optimizer):
    """Lookahead wrapper (Zhang et al. 2019): every k inner steps, the
    slow weights move alpha toward the fast weights and both sync
    (reference: incubate/optimizer/lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._parameter_list = inner_optimizer._parameter_list
        # slow weights snapshot the INITIAL fast weights (the first
        # sync must pull back toward w0, matching the reference)
        self._slow = {id(p): p._value
                      for p in (self._parameter_list or [])
                      if p is not None}

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        for p in self._parameter_list or []:
            if p is None:
                continue
            slow = self._slow[id(p)]
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            p._value = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "step_num": self._step_num}

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd["inner"])
        self._step_num = sd.get("step_num", 0)


class ModelAverage(Optimizer):
    """Running parameter average for evaluation (reference:
    incubate/optimizer/modelaverage.py): accumulates weights each step;
    apply() swaps the average in, restore() swaps back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameter_list = list(parameters) if parameters else []
        self._sum = {id(p): jnp.zeros_like(p._value)
                     for p in self._parameter_list}
        self._num = 0
        self._backup = None

    def step(self):
        for p in self._parameter_list:
            self._sum[id(p)] = self._sum[id(p)] + p._value
        self._num += 1

    def clear_grad(self):
        pass

    def apply(self, executor=None, need_restore=True):
        if not self._num:
            return
        self._backup = {id(p): p._value for p in self._parameter_list}
        for p in self._parameter_list:
            p._value = (self._sum[id(p)] / self._num).astype(
                p._value.dtype)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._parameter_list:
            p._value = self._backup[id(p)]
        self._backup = None
