"""Incubating APIs (reference: python/paddle/incubate/)."""
from . import asp  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401

__all__ = ["asp", "distributed", "nn"]
