"""Incubating APIs (reference: python/paddle/incubate/)."""
from . import asp  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401

__all__ = ["asp", "distributed", "nn"]

# incubate API tail (reference: python/paddle/incubate/__init__.py)
from ..geometric import (segment_max, segment_mean, segment_min,  # noqa: F401,E402
                         segment_sum)
from ..geometric import reindex_graph as graph_reindex  # noqa: F401,E402
from ..geometric import sample_neighbors as graph_sample_neighbors  # noqa: F401,E402
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401,E402


def identity_loss(x, reduction="none"):
    """(reference: incubate/operators/identity_loss — marks a loss for
    IPU pipelines; numerically identity with optional reduction)."""
    if reduction in ("mean", 1):
        return x.mean()
    if reduction in ("sum", 0):
        return x.sum()
    return x


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) fused (reference:
    incubate/operators/softmax_mask_fuse.py — XLA fuses the add into
    the softmax)."""
    from ..nn import functional as F

    return F.softmax(x + mask, axis=-1)


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (reference: softmax_mask_fuse_upper_
    triangle.py): positions above the diagonal are masked out."""
    from ..core.dispatch import def_op as _def_op

    global _smfut
    if "_smfut" not in globals():
        import jax
        import jax.numpy as jnp

        def _kernel(x):
            S = x.shape[-1]
            keep = jnp.tril(jnp.ones((x.shape[-2], S), bool))
            masked = jnp.where(keep, x, jnp.asarray(-1e30, x.dtype))
            return jax.nn.softmax(masked, axis=-1)

        _smfut = _def_op("fused_softmax_mask_upper_triangle")(_kernel)
    return _smfut(x)


from .optimizer import LookAhead, ModelAverage  # noqa: F401,E402

__all__ = __all__ + [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "graph_reindex", "graph_sample_neighbors", "graph_send_recv",
    "identity_loss", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "LookAhead", "ModelAverage",
]
