"""Incubating NN layers (reference: python/paddle/incubate/nn/).

Fused transformer-era layers land here (FusedMultiTransformer analog,
fused rms_norm/rope functional) — see ``functional``.
"""
from . import functional  # noqa: F401

__all__ = ["functional"]
