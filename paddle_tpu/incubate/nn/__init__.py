"""Incubating NN layers (reference: python/paddle/incubate/nn/).

FusedMultiTransformer and friends — the inference fast path
(see layer/fused_transformer.py); fused functional ops in functional/.
"""
from . import functional  # noqa: F401
from .layer import (FusedFeedForward, FusedMultiHeadAttention,
                    FusedMultiTransformer)  # noqa: F401

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedMultiTransformer"]
