from .fused_transformer import (FusedFeedForward, FusedMultiHeadAttention,
                                FusedMultiTransformer)  # noqa: F401

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedMultiTransformer"]
