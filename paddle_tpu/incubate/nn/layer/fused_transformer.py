"""Fused transformer layers (inference fast path).

(reference: python/paddle/incubate/nn/layer/fused_transformer.py —
FusedMultiHeadAttention:196, FusedFeedForward:502,
FusedMultiTransformer:1025 backed by the 2,023-LoC CUDA decoder
fused_multi_transformer_op.cu.h with cache-KV attention.)

TPU-native: each layer is a fusion *region* — LN → qkv matmul → flash /
cache attention → out proj → residual — expressed as consecutive jnp
ops that XLA fuses; the Pallas flash kernel handles the attention core
on TPU, and decode uses static preallocated caches updated by
dynamic_update_slice exactly like models/llama.py. One compiled program
per (prefill, decode) shape — the role of the reference's hand-written
CUDA decoder loop.
"""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ....nn import functional as F
from ....nn.layer import Layer
from ....nn.container import LayerList
from ....ops import manipulation as M
from ....ops import math as OM
from ....ops.attention import flash_attention
from ....tensor import Tensor

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedMultiTransformer"]


def _cache_attention(q, k_cache, v_cache, offset, S):
    from ....models.llama import _cache_attention as impl

    return impl(q, k_cache, v_cache, offset, S)


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN attention block with fused qkv
    (reference fused_transformer.py:196)."""

    def __init__(self, embed_dim: int, num_heads: int,
                 dropout_rate: float = 0.5, attn_dropout_rate: float = 0.5,
                 kdim=None, vdim=None, normalize_before: bool = False,
                 need_weights: bool = False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon: float = 1e-5,
                 nranks: int = 1, ring_id: int = -1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            (embed_dim, 3 * embed_dim), attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            (3 * embed_dim,), attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=linear_bias_attr, is_bias=True)
        from ....nn.initializer import Constant

        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            (embed_dim,), is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        x = query
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, self.pre_ln_scale, self.pre_ln_bias,
                             epsilon=self._epsilon)
        B, S = x.shape[0], x.shape[1]
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)
        qkv = M.reshape(qkv, (B, S, self.num_heads, 3 * self.head_dim))
        q, k, v = M.split(qkv, 3, axis=-1)
        causal = attn_mask is None  # decoder default: causal
        p = self.attn_dropout_rate if self.training else 0.0
        if p:
            from ....distributed.fleet.layers.mpu.random import \
                local_dropout_key

            out = flash_attention(q, k, v, causal=causal, dropout=p,
                                  dropout_key=local_dropout_key())
        else:
            out = flash_attention(q, k, v, causal=causal)
        out = M.reshape(out, (B, S, self.embed_dim))
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, p=self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, self.ln_scale, self.ln_bias,
                               epsilon=self._epsilon)
        return out


class FusedFeedForward(Layer):
    """(reference fused_transformer.py:502)."""

    def __init__(self, d_model: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, epsilon: float = 1e-5,
                 activation: str = "relu", act_dropout_rate=None,
                 normalize_before: bool = False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks: int = 1, ring_id: int = -1,
                 name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (act_dropout_rate
                                 if act_dropout_rate is not None
                                 else dropout_rate)
        self._epsilon = epsilon
        self._act = {"relu": F.relu, "gelu": F.gelu,
                     "silu": F.silu}[activation]
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            (dim_feedforward,), attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            (d_model,), attr=linear2_bias_attr, is_bias=True)
        from ....nn.initializer import Constant

        self.ln1_scale = self.create_parameter(
            (d_model,), default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter((d_model,), is_bias=True)
        self.ln2_scale = self.create_parameter(
            (d_model,), default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter((d_model,), is_bias=True)

    def forward(self, src):
        residual = src
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, self.ln1_scale, self.ln1_bias,
                             epsilon=self._epsilon)
        x = self._act(F.linear(x, self.linear1_weight, self.linear1_bias))
        x = F.dropout(x, p=self.act_dropout_rate, training=self.training)
        x = F.linear(x, self.linear2_weight, self.linear2_bias)
        x = F.dropout(x, p=self.dropout_rate, training=self.training)
        x = residual + x
        if not self.normalize_before:
            x = F.layer_norm(x, self.ln2_scale, self.ln2_bias,
                             epsilon=self._epsilon)
        return x


class FusedMultiTransformer(Layer):
    """Decoder stack with cache-KV generation
    (reference fused_transformer.py:1025 → CUDA
    fused_multi_transformer_op.cu.h).

    ``forward(src, caches=None, time_step=None)``:
    - training/no-cache: causal flash attention over the full sequence;
    - with caches (list of (k_cache, v_cache) raw head-major
      [B, H, M, D] arrays, the Pallas decode-kernel layout):
      writes the new kv at ``time_step`` and attends over the cache —
      prefill (S>1, time_step=0) and decode (S=1) share the path.
    """

    def __init__(self, embed_dim: int, num_heads: int,
                 dim_feedforward: int, dropout_rate: float = 0.0,
                 activation: str = "gelu", normalize_before: bool = True,
                 ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon: float = 1e-5, num_layers: int = -1,
                 nranks: int = 1, trans_qkvw: bool = True,
                 ring_id: int = -1, name=None):
        super().__init__()
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if qkv_weight_attrs else 1
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._act = {"relu": F.relu, "gelu": F.gelu,
                     "silu": F.silu}[activation]
        self.dropout_rate = dropout_rate
        mk = self.create_parameter
        from ....nn.initializer import Constant

        def plist(shape, bias=False, ones=False):
            return [mk(shape, is_bias=bias,
                       default_initializer=Constant(1.0) if ones else None)
                    for _ in range(num_layers)]

        self.ln_scales = plist((embed_dim,), ones=True)
        self.ln_biases = plist((embed_dim,), bias=True)
        self.qkv_weights = plist((embed_dim, 3 * embed_dim))
        self.qkv_biases = plist((3 * embed_dim,), bias=True)
        self.linear_weights = plist((embed_dim, embed_dim))
        self.linear_biases = plist((embed_dim,), bias=True)
        self.ffn_ln_scales = plist((embed_dim,), ones=True)
        self.ffn_ln_biases = plist((embed_dim,), bias=True)
        self.ffn1_weights = plist((embed_dim, dim_feedforward))
        self.ffn1_biases = plist((dim_feedforward,), bias=True)
        self.ffn2_weights = plist((dim_feedforward, embed_dim))
        self.ffn2_biases = plist((embed_dim,), bias=True)
        for group in ("ln_scales", "ln_biases", "qkv_weights", "qkv_biases",
                      "linear_weights", "linear_biases", "ffn_ln_scales",
                      "ffn_ln_biases", "ffn1_weights", "ffn1_biases",
                      "ffn2_weights", "ffn2_biases"):
            for i, p in enumerate(getattr(self, group)):
                self.add_parameter(f"{group}_{i}", p)

    def empty_caches(self, batch_size: int, max_len: int,
                     dtype=jnp.float32) -> List[Tuple]:
        shape = (batch_size, self.num_heads, max_len, self.head_dim)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(self.num_layers)]

    def _layer(self, i, x, cache, offset):
        B, S = x.shape[0], x.shape[1]
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, self.ln_scales[i], self.ln_biases[i],
                             epsilon=self._epsilon)
        qkv = F.linear(x, self.qkv_weights[i], self.qkv_biases[i])
        qkv = M.reshape(qkv, (B, S, self.num_heads, 3 * self.head_dim))
        q, k, v = M.split(qkv, 3, axis=-1)
        new_cache = None
        if cache is not None:
            k_cache, v_cache = cache    # head-major [B, H, M, D]
            k_cache = lax.dynamic_update_slice_in_dim(
                k_cache, jnp.swapaxes(k._value, 1, 2).astype(k_cache.dtype),
                offset, axis=2)
            v_cache = lax.dynamic_update_slice_in_dim(
                v_cache, jnp.swapaxes(v._value, 1, 2).astype(v_cache.dtype),
                offset, axis=2)
            ov = _cache_attention(q._value, k_cache, v_cache, offset, S)
            out = Tensor(ov.reshape(B, S, self.embed_dim),
                         stop_gradient=True)
            new_cache = (k_cache, v_cache)
        else:
            out = flash_attention(q, k, v, causal=True)
            out = M.reshape(out, (B, S, self.embed_dim))
        out = F.linear(out, self.linear_weights[i], self.linear_biases[i])
        x = residual + out
        residual = x
        if self.normalize_before:
            h = F.layer_norm(x, self.ffn_ln_scales[i],
                             self.ffn_ln_biases[i], epsilon=self._epsilon)
        else:
            h = x
        h = self._act(F.linear(h, self.ffn1_weights[i],
                               self.ffn1_biases[i]))
        h = F.linear(h, self.ffn2_weights[i], self.ffn2_biases[i])
        x = residual + h
        if not self.normalize_before:
            x = F.layer_norm(x, self.ffn_ln_scales[i],
                             self.ffn_ln_biases[i], epsilon=self._epsilon)
        return x, new_cache

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                time_step=None, **kw):
        offset = 0
        if time_step is not None:
            # int or traced scalar — dynamic_update_slice takes both
            offset = time_step._value if isinstance(time_step, Tensor) \
                else time_step
        x = src
        new_caches = []
        for i in range(self.num_layers):
            cache = caches[i] if caches is not None else None
            x, nc = self._layer(i, x, cache, offset)
            if caches is not None:
                new_caches.append(nc)
        if caches is not None:
            return x, new_caches
        return x
