"""Fused functional ops (reference: python/paddle/incubate/nn/functional/
— fused_rms_norm, fused_layer_norm, fused_rotary_position_embedding,
fused_bias_act, fused_dropout_add, swiglu).

Each fuses into the surrounding XLA program; on TPU the rms_norm and
flash-attention paths dispatch to the Pallas kernels (ops/pallas/).
"""
from __future__ import annotations

from typing import Optional

from ....ops import nn_ops as _nn
from ....ops.nn_ops import fused_rope as _fused_rope
from ....tensor import Tensor

import jax
import jax.numpy as jnp

__all__ = [
    "fused_rms_norm", "fused_layer_norm",
    "fused_rotary_position_embedding", "fused_bias_act",
    "fused_dropout_add", "swiglu", "fused_linear",
    "fused_multi_transformer", "masked_multihead_attention",
    "block_multihead_attention",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    """(reference: incubate/nn/functional/fused_rms_norm.py →
    phi/kernels/gpu/rms_norm_kernel.cu). Returns (out, residual_out) like
    the reference when a residual is supplied, else out."""
    from ....core.enforce import enforce as _enf

    _enf(quant_scale in (-1, None),
         "fused_rms_norm: in-kernel output quantization is served by "
         "nn.quant on TPU — leave quant_scale at -1")
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = _nn.rms_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                       begin_norm_axis=begin_norm_axis)
    if residual is not None:
        return out, residual_out
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    """(reference: phi/kernels/fusion/gpu/fused_layernorm_kernel.cu —
    residual-add + layernorm fusion)."""
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = _nn.layer_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                         begin_norm_axis=begin_norm_axis)
    if residual is not None:
        return out, residual_out
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True, **kw):
    """(reference: incubate/nn/functional/fused_rotary_position_embedding
    → phi/kernels/fusion/gpu/fused_rope_kernel.cu; SPMD rule
    spmd_rules/fused_rope.cc). q/k: [B, S, H, D]; returns the same tuple
    arity as the reference (q, k, v)."""
    from ....core.enforce import enforce as _enf

    _enf(use_neox_rotary_style,
         "fused_rotary_position_embedding: only the neox (rotate-half) "
         "style is served on TPU (ops/nn_ops.fused_rope); the GPT-J "
         "interleaved style is not implemented — pass "
         "use_neox_rotary_style=True")
    outs = _fused_rope(q, q if k is None else k, cos, sin,
                       position_ids=position_ids)
    q_out, k_out = outs if isinstance(outs, (tuple, list)) else (outs, None)
    return q_out, (None if k is None else k_out), v


def fused_bias_act(x, bias=None, act_method: str = "gelu", **kw):
    """(reference: phi/kernels/fusion/gpu/fused_bias_act_kernel.cu)."""
    from ....nn import functional as F

    if bias is not None:
        x = x + bias
    act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu,
           "swiglu": swiglu, "geglu": None}.get(act_method)
    if act_method == "geglu":
        from ....ops import manipulation as M

        a, b = M.split(x, 2, axis=-1)
        return F.gelu(a) * b
    if act is None:
        raise ValueError(f"unknown act_method {act_method!r}")
    return act(x)


def swiglu(x, y=None):
    """(reference: incubate/nn/functional/swiglu → phi swiglu kernel).
    swiglu(x, y) = silu(x) * y; single-arg form splits x in half."""
    from ....nn import functional as F

    if y is None:
        from ....ops import manipulation as M

        x, y = M.split(x, 2, axis=-1)
    return F.silu(x) * y


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      **kw):
    """(reference: phi/kernels/fusion/gpu/fused_dropout_add_kernel.cu)."""
    from ....nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_linear(x, weight, bias=None, transpose_weight=False, **kw):
    """(reference: fused_gemm_epilogue — cuBLASLt matmul+bias; XLA fuses
    the epilogue natively on the MXU)."""
    from ....ops import math as M

    out = M.matmul(x, weight, transpose_y=transpose_weight)
    if bias is not None:
        out = out + bias
    return out


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1,
                               rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """One fused decode step of cache-KV attention (reference:
    incubate/nn/functional/masked_multihead_attention.py:19 over
    masked_multihead_attention_kernel.cu).

    x: [B, 3*H*D] fused qkv of the new token; cache_kv: [2, B, H, M, D];
    sequence_lengths: [B, 1] per-row write/attend offsets (the ragged
    primitive of ops/pallas/decode_attention.py). Returns
    (out [B, H*D], updated cache_kv). src_mask/cum_offsets/
    beam_cache_offset and the quant knobs are NOT served here (the TPU
    path masks by the per-row frontier, packs via the Predictor, and
    quantizes via nn.quant) — they are enforced to their defaults so
    divergence is loud, mirroring block_multihead_attention."""
    from ....ops.pallas.decode_attention import _dense_ragged
    from ....core.enforce import enforce as _enf

    for knob, name in ((src_mask, "src_mask"),
                       (cum_offsets, "cum_offsets"),
                       (beam_cache_offset, "beam_cache_offset"),
                       (rotary_tensor, "rotary_tensor"),
                       (qkv_out_scale, "qkv_out_scale"),
                       (out_shift, "out_shift"),
                       (out_smooth, "out_smooth")):
        _enf(knob is None,
             f"masked_multihead_attention: {name} is not served by the "
             "TPU decode step (masking is the per-row frontier, "
             "packing is the Predictor serving path, quantization is "
             "nn.quant) — pass None")
    _enf(out_scale in (-1, None) and compute_dtype == "default"
         and quant_round_type == 1 and quant_max_bound == 127.0
         and quant_min_bound == -127.0,
         "masked_multihead_attention: output/cache quantization is "
         "served by nn.quant on TPU, not in-kernel — leave the quant "
         "knobs at their defaults")
    _enf(seq_len == 1, "masked_multihead_attention decodes one token "
                       "per row (seq_len must be 1)")
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    cv = cache_kv._value if isinstance(cache_kv, Tensor) \
        else jnp.asarray(cache_kv)
    _enf(cv.ndim == 5 and cv.shape[0] == 2,
         "cache_kv must be [2, B, H, max_seq, D]")
    B = xv.shape[0]
    _, _, H, M, D = cv.shape
    qkv = xv.reshape(B, 3, H, D)
    if bias is not None:
        bv = bias._value if isinstance(bias, Tensor) else jnp.asarray(bias)
        qkv = qkv + bv.reshape(1, 3, H, D)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # [B, H, D]
    if sequence_lengths is not None:
        sl = sequence_lengths._value if isinstance(
            sequence_lengths, Tensor) else jnp.asarray(sequence_lengths)
        off = sl.reshape(B).astype(jnp.int32)
    else:
        off = jnp.zeros((B,), jnp.int32)
    from ....core.enforce import enforce as _enf2
    _enf2(rotary_emb_dims == 0 and not use_neox_rotary_style,
          "masked_multihead_attention: apply rotary embeddings at the "
          "model level (ops/nn_ops.fused_rope); the fused in-kernel "
          "rotary path (rotary_emb_dims/use_neox_rotary_style) is not "
          "provided here")
    k_cache = cv[0].at[jnp.arange(B), :, off, :].set(
        k.astype(cv.dtype))
    v_cache = cv[1].at[jnp.arange(B), :, off, :].set(
        v.astype(cv.dtype))
    out = _dense_ragged(q[:, None], k_cache, v_cache, off)
    new_cache = jnp.stack([k_cache, v_cache])
    return (Tensor(out.reshape(B, H * D), stop_gradient=True),
            Tensor(new_cache, stop_gradient=True))


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-5,
                            cache_kvs=None, pre_caches=None, seq_lens=None,
                            rotary_embs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            rotary_emb_dims=0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, name=None,
                            num_heads=None):
    """Stateless functional form of the FusedMultiTransformer stack
    (num_heads: required with 2-D [h, 3h] qkv weights; inferred from
    the reference 4-D layout or the caches otherwise).
    (reference: incubate/nn/functional/fused_transformer.py:964 over
    fused_multi_transformer_op.cu.h — here the same math as
    incubate.nn.FusedMultiTransformer._layer, with caller-owned weight
    lists). qkv_weights: per layer [3*h, h] when trans_qkvw (reference
    default) else [h, 3*h]. Returns out, or (out, cache_kvs) when
    caches are passed."""
    from ....nn import functional as F
    from ....ops import manipulation as M
    from ....nn.functional import flash_attention
    from ....models.llama import _cache_attention
    from ....core.enforce import enforce as _enf

    for knob, kname in ((pre_caches, "pre_caches"),
                        (seq_lens, "seq_lens"),
                        (rotary_embs, "rotary_embs"),
                        (attn_mask, "attn_mask")):
        _enf(knob is None,
             f"fused_multi_transformer: {kname} is not served by this "
             "functional form (ragged/packed prefill is the Predictor "
             "serving path, rotary embeddings apply at the model level "
             "via ops/nn_ops.fused_rope, masking is causal+frontier) — "
             "pass None")
    _enf(rotary_emb_dims == 0,
         "fused_multi_transformer: in-kernel rotary "
         "(rotary_emb_dims != 0) is not served; apply "
         "ops/nn_ops.fused_rope at the model level")
    _enf(ring_id == -1,
         "fused_multi_transformer: ring_id tensor-parallelism is the "
         "distributed engine's job (distributed/engine.py shards the "
         "weights); pass ring_id=-1")

    def val(t):
        return t._value if isinstance(t, Tensor) else jnp.asarray(t)

    xv = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    B, S = xv.shape[0], xv.shape[1]
    offset = 0
    if time_step is not None:
        offset = (time_step._value if isinstance(time_step, Tensor)
                  else time_step)
    act = {"relu": F.relu, "gelu": F.gelu, "silu": F.silu}[activation]
    n_layers = len(qkv_weights)
    new_caches = []
    h = xv
    for i in range(n_layers):
        residual = h
        if pre_layer_norm:
            h = F.layer_norm(h, ln_scales[i], ln_biases[i],
                             epsilon=epsilon)
        qw = val(qkv_weights[i])
        embed_dim = residual.shape[-1]
        # reference qkv weight: [3, num_head, head_dim, h] when
        # trans_qkvw (default) else [h, 3, num_head, head_dim]
        if qw.ndim == 4:
            Hn = qw.shape[1] if trans_qkvw else qw.shape[2]
        elif num_heads is not None:
            Hn = int(num_heads)
        elif cache_kvs is not None:
            Hn = (cache_kvs[i][0].shape[1]
                  if isinstance(cache_kvs[i], (tuple, list))
                  else val(cache_kvs[i]).shape[2])
        else:
            from ....core.enforce import enforce as _enf3

            _enf3(False,
                  "fused_multi_transformer: with 2-D qkv weights pass "
                  "num_heads= (the reference's 4-D [3, num_head, "
                  "head_dim, h] layout carries it implicitly)")
        Dh = embed_dim // Hn
        if trans_qkvw:
            qw = qw.reshape(-1, qw.shape[-1]).T     # [h, 3h]
        else:
            qw = qw.reshape(qw.shape[0], -1)
        qkv_v = h._value @ qw.astype(h._value.dtype)
        if qkv_biases is not None and qkv_biases[i] is not None:
            qkv_v = qkv_v + val(qkv_biases[i]).reshape(-1)
        if val(qkv_weights[i]).ndim == 4:
            # reference layout: qkv-major (q all heads, k, v)
            qkv5 = qkv_v.reshape(B, S, 3, Hn, Dh)
            q = Tensor(qkv5[:, :, 0])
            k = Tensor(qkv5[:, :, 1])
            v = Tensor(qkv5[:, :, 2])
        else:
            # 2-D [h, 3*h] layer convention: head-major, qkv within
            qkv4 = M.reshape(Tensor(qkv_v), (B, S, Hn, 3 * Dh))
            q, k, v = M.split(qkv4, 3, axis=-1)
        if cache_kvs is not None:
            c = cache_kvs[i]
            if not isinstance(c, (tuple, list)):
                cv = val(c)
                c = (cv[0], cv[1])
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                c[0], jnp.swapaxes(k._value, 1, 2).astype(c[0].dtype),
                offset, axis=2)
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                c[1], jnp.swapaxes(v._value, 1, 2).astype(c[1].dtype),
                offset, axis=2)
            ov = _cache_attention(q._value, k_cache, v_cache, offset, S)
            out = Tensor(ov.reshape(B, S, embed_dim), stop_gradient=True)
            new_caches.append((k_cache, v_cache))
        else:
            out = flash_attention(q, k, v, causal=True)[0]
            out = M.reshape(out, (B, S, embed_dim))
        out = F.linear(out, linear_weights[i], linear_biases[i])
        if dropout_rate:
            # reference: residual + dropout(attn_out) (fused_transformer
            # pseudo-code); same placement after the ffn below
            out = F.dropout(out, p=dropout_rate, training=training,
                            mode=mode)
        h = residual + out
        if not pre_layer_norm:
            # post-LN: the attention block's LayerNorm applies AFTER
            # its residual (reference pseudo-code, fused_transformer.py)
            h = F.layer_norm(h, ln_scales[i], ln_biases[i],
                             epsilon=epsilon)
        residual = h
        if pre_layer_norm:
            f = F.layer_norm(h, ffn_ln_scales[i], ffn_ln_biases[i],
                             epsilon=epsilon)
        else:
            f = h
        f = act(F.linear(f, ffn1_weights[i], ffn1_biases[i]))
        f = F.linear(f, ffn2_weights[i], ffn2_biases[i])
        if dropout_rate:
            f = F.dropout(f, p=dropout_rate, training=training,
                          mode=mode)
        h = residual + f
        if not pre_layer_norm:
            h = F.layer_norm(h, ffn_ln_scales[i], ffn_ln_biases[i],
                             epsilon=epsilon)
    if cache_kvs is not None:
        return h, new_caches
    return h


def block_multihead_attention(qkv, key_cache, value_cache,
                              seq_lens_encoder, seq_lens_decoder,
                              seq_lens_this_time, padding_offsets,
                              cum_offsets, cu_seqlens_q, cu_seqlens_k,
                              block_tables, pre_key_cache=None,
                              pre_value_cache=None,
                              cache_k_quant_scales=None,
                              cache_v_quant_scales=None,
                              cache_k_dequant_scales=None,
                              cache_v_dequant_scales=None,
                              qkv_out_scale=None, qkv_bias=None,
                              out_shift=None, out_smooth=None,
                              rope_emb=None, mask=None, tgt_mask=None,
                              max_seq_len=-1, block_size=64,
                              use_neox_style=False,
                              use_dynamic_cachekv_quant=False,
                              quant_round_type=1, quant_max_bound=127.0,
                              quant_min_bound=-127.0, out_scale=-1,
                              compute_dtype="default"):
    """Paged (block-table) KV-cache attention, decode phase (reference:
    incubate/nn/functional/block_multihead_attention.py:19 over
    phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu).

    The TPU redesign of the paged cache lives in
    ops/pallas/decode_attention.paged_decode_attention (the physical
    page id is gathered from a scalar-prefetched block table inside the
    BlockSpec index map); this wrapper serves the reference surface for
    the DECODE phase: one new token per row (seq_lens_this_time == 1),
    per-row write position = seq_lens_decoder, ragged frontiers. The
    encoder/prefill phase, cache quantization, in-kernel rope, and
    pre-caches are served by the Predictor paged path (inference/
    __init__.py) and nn.quant — pass those knobs there.

    Returns (out [B, H*D], qkv, key_cache, value_cache) with the
    caches functionally updated (immutable arrays: returned, the
    reference updates in place).
    """
    from ....core.enforce import enforce as _enf
    from ....ops.pallas.decode_attention import (paged_attention_dense,
                                                 paged_supported,
                                                 paged_decode_attention)
    from ....core import flags as _flags

    for knob, name in ((pre_key_cache, "pre_key_cache"),
                       (pre_value_cache, "pre_value_cache"),
                       (cache_k_quant_scales, "cache_k_quant_scales"),
                       (cache_v_quant_scales, "cache_v_quant_scales"),
                       (cache_k_dequant_scales, "cache_k_dequant_scales"),
                       (cache_v_dequant_scales, "cache_v_dequant_scales"),
                       (qkv_out_scale, "qkv_out_scale"),
                       (out_shift, "out_shift"),
                       (out_smooth, "out_smooth"),
                       (rope_emb, "rope_emb"),
                       (mask, "mask"), (tgt_mask, "tgt_mask")):
        _enf(knob is None,
             f"block_multihead_attention: {name} is served by the "
             "Predictor paged path / nn.quant on TPU, not in-kernel")
    _enf(not use_dynamic_cachekv_quant and out_scale in (-1, None)
         and compute_dtype == "default" and quant_round_type == 1
         and quant_max_bound == 127.0 and quant_min_bound == -127.0,
         "block_multihead_attention: cache-kv quantization / output "
         "quant are served by nn.quant on TPU, not in-kernel — leave "
         "the quant knobs at their defaults")
    for knob, kname in ((padding_offsets, "padding_offsets"),
                        (cum_offsets, "cum_offsets"),
                        (cu_seqlens_q, "cu_seqlens_q"),
                        (cu_seqlens_k, "cu_seqlens_k")):
        _enf(knob is None,
             f"block_multihead_attention: {kname} is ragged-prefill "
             "packing metadata, served by the Predictor paged path "
             "(inference/__init__.py) — pass None in the decode phase")
    _enf(not use_neox_style,
         "block_multihead_attention: in-kernel neox rope is not served "
         "(rope applies at the model level via ops/nn_ops.fused_rope)")
    qv = qkv._value if isinstance(qkv, Tensor) else jnp.asarray(qkv)
    kp = key_cache._value if isinstance(key_cache, Tensor) \
        else jnp.asarray(key_cache)
    vp = value_cache._value if isinstance(value_cache, Tensor) \
        else jnp.asarray(value_cache)
    tbl = block_tables._value if isinstance(block_tables, Tensor) \
        else jnp.asarray(block_tables)
    sld = seq_lens_decoder._value if isinstance(seq_lens_decoder,
                                                Tensor) \
        else jnp.asarray(seq_lens_decoder)
    B = tbl.shape[0]
    P, KV, page, D = kp.shape
    _enf(block_size == page,
         lambda: f"block_multihead_attention: block_size ({block_size}) "
                 f"does not match the physical cache page size ({page}) "
                 "— the page size is fixed by the cache layout "
                 "[P, KV, page, D], it cannot be re-specified per call")
    _enf(max_seq_len in (-1, tbl.shape[1] * page),
         lambda: f"block_multihead_attention: max_seq_len "
                 f"({max_seq_len}) disagrees with the block-table "
                 f"capacity ({tbl.shape[1]} pages x {page}); pass -1 "
                 "(the capacity is fixed by the table shape)")
    import numpy as _np

    def _host(v):
        a = v._value if isinstance(v, Tensor) else v
        return None if isinstance(a, jax.core.Tracer) else _np.asarray(a)

    if seq_lens_encoder is not None:
        enc = _host(seq_lens_encoder)
        _enf(enc is None or bool((enc == 0).all()),
             "block_multihead_attention: this wrapper serves the DECODE "
             "phase only (seq_lens_encoder must be all zero); the "
             "encoder/prefill phase is the Predictor paged path")
    if seq_lens_this_time is not None:
        this = _host(seq_lens_this_time)
        _enf(this is None or bool((this == 1).all()),
             "block_multihead_attention: decode phase writes ONE new "
             "token per row (seq_lens_this_time must be all one); "
             "ragged prefill is the Predictor paged path")
    _enf(qv.shape[0] == B and qv.ndim == 2,
         "decode phase: qkv is [batchsize, 3*num_head*head_dim] "
         "(one new token per row; ragged prefill is the Predictor "
         "paged path)")
    # GQA layout (reference): qkv packs (H + 2*KV) head planes of D
    total_heads = qv.shape[1] // D
    _enf(qv.shape[1] % D == 0 and total_heads > 2 * KV,
         lambda: "block_multihead_attention: qkv width "
                 f"{qv.shape[1]} is not (num_q_heads + 2*{KV})*{D}")
    H = total_heads - 2 * KV
    if qkv_bias is not None:
        bv = qkv_bias._value if isinstance(qkv_bias, Tensor) \
            else jnp.asarray(qkv_bias)
        qv = qv + bv.reshape(1, -1)
    heads = qv.reshape(B, total_heads, D)
    q = heads[:, :H]                                       # [B, H, D]
    kw = heads[:, H:H + KV]                                # [B, KV, D]
    vw = heads[:, H + KV:]
    off = sld.reshape(B).astype(jnp.int32)
    if not isinstance(off, jax.core.Tracer):
        _enf(bool((_np.asarray(off) < tbl.shape[1] * page).all()),
             lambda: "block_multihead_attention: a row's "
                     "seq_lens_decoder exceeds its block table "
                     f"({tbl.shape[1]} pages x {page}); allocate more "
                     "pages")
    pid = jnp.take_along_axis(tbl.astype(jnp.int32),
                              (off // page)[:, None], axis=1)[:, 0]
    slot = off % page
    kp = kp.at[pid, :, slot, :].set(kw.astype(kp.dtype))
    vp = vp.at[pid, :, slot, :].set(vw.astype(vp.dtype))
    q4 = q[:, None]                                        # [B,1,H,D]
    if (_flags._get("use_pallas_kernels", True)
            and paged_supported(q4.shape, kp.shape)
            and jax.default_backend() != "cpu"):
        out = paged_decode_attention(q4, kp, vp, tbl, off)
    else:
        out = paged_attention_dense(q4, kp, vp, tbl, off)
    return (Tensor(out.reshape(B, H * D), stop_gradient=True),
            Tensor(qv, stop_gradient=True),
            Tensor(kp, stop_gradient=True),
            Tensor(vp, stop_gradient=True))
