"""Fused functional ops (reference: python/paddle/incubate/nn/functional/).

The Pallas/XLA fused kernels register here under the reference names;
see ops/fused.py for the kernel implementations.
"""

__all__ = []
