"""Fused functional ops (reference: python/paddle/incubate/nn/functional/
— fused_rms_norm, fused_layer_norm, fused_rotary_position_embedding,
fused_bias_act, fused_dropout_add, swiglu).

Each fuses into the surrounding XLA program; on TPU the rms_norm and
flash-attention paths dispatch to the Pallas kernels (ops/pallas/).
"""
from __future__ import annotations

from typing import Optional

from ....ops import nn_ops as _nn
from ....ops.nn_ops import fused_rope as _fused_rope
from ....tensor import Tensor

__all__ = [
    "fused_rms_norm", "fused_layer_norm",
    "fused_rotary_position_embedding", "fused_bias_act",
    "fused_dropout_add", "swiglu", "fused_linear",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    """(reference: incubate/nn/functional/fused_rms_norm.py →
    phi/kernels/gpu/rms_norm_kernel.cu). Returns (out, residual_out) like
    the reference when a residual is supplied, else out."""
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = _nn.rms_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                       begin_norm_axis=begin_norm_axis)
    if residual is not None:
        return out, residual_out
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    """(reference: phi/kernels/fusion/gpu/fused_layernorm_kernel.cu —
    residual-add + layernorm fusion)."""
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = _nn.layer_norm(x, norm_weight, norm_bias, epsilon=epsilon,
                         begin_norm_axis=begin_norm_axis)
    if residual is not None:
        return out, residual_out
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True, **kw):
    """(reference: incubate/nn/functional/fused_rotary_position_embedding
    → phi/kernels/fusion/gpu/fused_rope_kernel.cu; SPMD rule
    spmd_rules/fused_rope.cc). q/k: [B, S, H, D]; returns the same tuple
    arity as the reference (q, k, v)."""
    outs = _fused_rope(q, q if k is None else k, cos, sin,
                       position_ids=position_ids)
    q_out, k_out = outs if isinstance(outs, (tuple, list)) else (outs, None)
    return q_out, (None if k is None else k_out), v


def fused_bias_act(x, bias=None, act_method: str = "gelu", **kw):
    """(reference: phi/kernels/fusion/gpu/fused_bias_act_kernel.cu)."""
    from ....nn import functional as F

    if bias is not None:
        x = x + bias
    act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu,
           "swiglu": swiglu, "geglu": None}.get(act_method)
    if act_method == "geglu":
        from ....ops import manipulation as M

        a, b = M.split(x, 2, axis=-1)
        return F.gelu(a) * b
    if act is None:
        raise ValueError(f"unknown act_method {act_method!r}")
    return act(x)


def swiglu(x, y=None):
    """(reference: incubate/nn/functional/swiglu → phi swiglu kernel).
    swiglu(x, y) = silu(x) * y; single-arg form splits x in half."""
    from ....nn import functional as F

    if y is None:
        from ....ops import manipulation as M

        x, y = M.split(x, 2, axis=-1)
    return F.silu(x) * y


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      **kw):
    """(reference: phi/kernels/fusion/gpu/fused_dropout_add_kernel.cu)."""
    from ....nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_linear(x, weight, bias=None, transpose_weight=False, **kw):
    """(reference: fused_gemm_epilogue — cuBLASLt matmul+bias; XLA fuses
    the epilogue natively on the MXU)."""
    from ....ops import math as M

    out = M.matmul(x, weight, transpose_y=transpose_weight)
    if bias is not None:
        out = out + bias
    return out
