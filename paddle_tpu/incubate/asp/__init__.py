"""ASP — automatic structured (n:m) sparsity.

(reference: python/paddle/incubate/asp/ — asp.py prune_model/decorate,
utils.py check_mask_1d/get_mask_1d etc.; the 2:4 pattern targets sparse
tensor cores. On TPU there is no 2:4 hardware unit — the value here is
the PRUNING WORKFLOW parity: magnitude-based n:m masks, mask
re-application after each optimizer step, sparsity checkers — producing
models exportable to sparse-capable backends.)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ...autograd import no_grad
from ...nn.layer import Layer

__all__ = ["prune_model", "decorate", "calculate_density",
           "check_sparsity", "reset_excluded_layers",
           "set_excluded_layers"]

# masks hold a STRONG ref to their parameter: id() keys alone could be
# recycled by a GC'd model and silently mask an unrelated tensor
_masks: Dict[int, tuple] = {}     # id -> (param, mask)
_excluded: Dict[int, object] = {}  # id -> param


def calculate_density(x) -> float:
    """(reference asp/utils.py calculate_density)"""
    arr = np.asarray(getattr(x, "_value", x))
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _nm_mask(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the ``n`` largest-magnitude entries of every group of ``m``
    along the input dim (mask_1d of reference asp/utils.py)."""
    shape = w.shape
    flat = np.abs(w.reshape(-1, shape[-1]))
    pad = (-flat.shape[1]) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)
    order = np.argsort(groups, axis=-1)  # ascending
    mask = np.ones_like(groups, dtype=bool)
    drop = order[..., :m - n]
    np.put_along_axis(mask, drop, False, axis=-1)
    mask = mask.reshape(flat.shape[0], -1)[:, :shape[-1]]
    return mask.reshape(shape)


def check_sparsity(x, n: int = 2, m: int = 4) -> bool:
    """True iff every group of m entries along the last dim has at most
    n nonzeros (reference check_mask_1d)."""
    arr = np.asarray(getattr(x, "_value", x))
    flat = arr.reshape(-1, arr.shape[-1])
    pad = (-flat.shape[1]) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)
    return bool((np.count_nonzero(groups, axis=-1) <= n).all())


def set_excluded_layers(model, layer_names: List[str]) -> None:
    for name, sub in model.named_sublayers():
        if name in layer_names:
            for p in sub.parameters(include_sublayers=False):
                _excluded[id(p)] = p


def reset_excluded_layers(model=None) -> None:
    if model is None:
        _excluded.clear()
        return
    for p in model.parameters():
        _excluded.pop(id(p), None)


def _prunable(name: str, p) -> bool:
    return (p is not None and p.trainable and p._value.ndim == 2
            and id(p) not in _excluded and "weight" in name)


@no_grad()
def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m magnitude pruning to every eligible 2-D weight and
    remember the masks (reference asp.py prune_model)."""
    masks = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        w = np.asarray(p._value)
        mask = _nm_mask(w, n, m)
        p._value = jnp.asarray(w * mask, p._value.dtype)
        if with_mask:
            _masks[id(p)] = (p, jnp.asarray(mask, p._value.dtype))
            masks[name] = mask
    return masks


class _ASPOptimizer:
    """Re-applies the sparsity masks after every step (reference
    asp.py decorate → OptimizerWithSparsityGuarantee)."""

    def __init__(self, inner):
        self._inner_opt = inner

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    @no_grad()
    def step(self):
        self._inner_opt.step()
        for p in self._inner_opt._parameter_list or []:
            entry = _masks.get(id(p))
            if entry is not None and entry[0] is p:  # identity-checked
                p._value = p._value * entry[1]

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)


def decorate(optimizer) -> _ASPOptimizer:
    return _ASPOptimizer(optimizer)
