"""Mixture-of-Experts with expert parallelism
(reference: python/paddle/incubate/distributed/models/moe/)."""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401

__all__ = ["MoELayer", "BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]
