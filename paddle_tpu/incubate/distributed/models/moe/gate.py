"""MoE gates: naive top-k, Switch (top-1), GShard (top-2).

TPU-native re-design of the reference's gate zoo
(reference: python/paddle/incubate/distributed/models/moe/gate/
naive_gate.py, switch_gate.py, gshard_gate.py, base_gate.py).

The reference gates emit per-token expert indices consumed by the
variable-length ``global_scatter`` CUDA op. XLA needs static shapes, so
here a gate is a *policy object* — (top_k, capacity_factor, jitter,
aux-loss style) — and the dense capacity-C dispatch/combine tensors are
built inside the MoE kernel (moe_layer.py::_topk_dispatch), the standard
GShard einsum formulation that maps onto the MXU.

The gate projection weight lives in the gate (a Layer, reference parity)
and is replicated across expert-parallel ranks.
"""
from __future__ import annotations

from typing import Optional

from .....nn.layer import Layer

__all__ = ["BaseGate", "NaiveGate", "SwitchGate", "GShardGate"]


class BaseGate(Layer):
    """Holds the [d_model, num_experts] router projection + policy knobs."""

    top_k = 1
    capacity_factor: Optional[float] = None  # None → no token dropping
    jitter = 0.0

    def __init__(self, d_model: int, num_experts: int, weight_attr=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.weight = self.create_parameter((d_model, num_experts))
        self._loss = None

    def get_loss(self, clear: bool = True):
        """The auxiliary load-balancing loss of the last forward
        (reference base_gate.py:49 set_loss/get_loss)."""
        loss = self._loss
        if clear:
            self._loss = None
        return loss

    def set_loss(self, loss):
        self._loss = loss

    def extra_repr(self):
        return (f"d={self.d_model}, experts={self.num_experts}, "
                f"k={self.top_k}, cf={self.capacity_factor}")


class NaiveGate(BaseGate):
    """Plain top-k routing, generous capacity (reference naive_gate.py)."""

    def __init__(self, d_model, num_experts, topk: int = 2, **kw):
        super().__init__(d_model, num_experts)
        self.top_k = topk
        self.capacity_factor = None


class SwitchGate(BaseGate):
    """Switch-Transformer top-1 gate with capacity
    (reference switch_gate.py — topk=1, capacity via switch_capacity)."""

    def __init__(self, d_model, num_experts, topk: int = 1,
                 capacity: float = 1.25, **kw):
        super().__init__(d_model, num_experts)
        if topk != 1:
            raise ValueError("SwitchGate is top-1 by definition; use "
                             "GShardGate or NaiveGate for top-k routing")
        self.top_k = 1
        self.capacity_factor = capacity


class GShardGate(BaseGate):
    """GShard top-k gate with capacity and load-balance loss
    (reference gshard_gate.py — topk=2, capacity=(1.2, 2.4)).
    ``random_routing`` (probability-proportional 2nd-expert drop) is not
    implemented — routing is deterministic top-k."""

    def __init__(self, d_model, num_experts, topk: int = 2,
                 capacity: float = 2.0, random_routing: bool = False, **kw):
        super().__init__(d_model, num_experts)
        if random_routing:
            raise NotImplementedError(
                "GShardGate random_routing is not implemented; pass "
                "random_routing=False for deterministic top-k")
        self.top_k = topk
        self.capacity_factor = capacity
