"""Mixture-of-Experts layer with expert parallelism over ICI.

TPU-native re-design of the reference's MoELayer
(reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
— per-token expert indices + variable-length ``global_scatter`` /
``global_gather`` CUDA all-to-alls,
fluid/operators/collective/global_scatter_op.cu.cc).

XLA needs static shapes, so routing uses the dense GShard capacity-C
formulation instead of variable-length scatter: the gate builds
``dispatch``/``combine`` one-hot tensors [T, E, C] and the dispatch,
expert FFN, and combine are three einsums (MXU-bound) around a pair of
``lax.all_to_all`` collectives on the expert-parallel mesh axes — the
same math GShard/Switch run on TPU pods. Tokens beyond an expert's
capacity are dropped (gshard/switch) or capacity is set to T (naive gate,
no dropping).

Expert weights are *stacked*: one [E, d, h] tensor sharded over the
expert axes on dim 0, so each rank physically holds E/n experts and the
expert FFN is a single batched einsum rather than a Python loop over
expert modules (the reference loops over ``self.experts`` per rank).
"""
from __future__ import annotations

import math
from functools import partial
from typing import List, Optional

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .....autograd import engine as _engine
from .....core.enforce import enforce
from .....distributed import collective as C
from .....nn.layer import Layer
from .....observability import moestats as _moestats
from .....tensor import Tensor
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]


def _topk_dispatch(probs, k: int, cap: int):
    """Dense top-k dispatch/combine [T, E, C] + switch-style aux loss."""
    T, E = probs.shape
    masks, gates = [], []
    remaining = probs
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=probs.dtype)
        masks.append(m)
        gates.append(jnp.sum(probs * m, axis=-1))
        remaining = remaining * (1.0 - m)
    # load-balance loss: E * sum_e fraction_tokens(e) * mean_prob(e)
    density = jnp.mean(masks[0], axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * E

    denom = sum(gates) + 1e-9
    combine = jnp.zeros((T, E, cap), probs.dtype)
    offset = jnp.zeros((E,), probs.dtype)
    for j, m in enumerate(masks):
        # queue position of each token at its chosen expert; later-k
        # choices queue behind all earlier-k traffic (GShard priority)
        pos = jnp.cumsum(m, axis=0) - m + offset[None, :]
        pos_t = jnp.sum(pos * m, axis=-1)
        keep = ((pos_t < cap) & (jnp.sum(m, axis=-1) > 0)).astype(
            probs.dtype)
        gate_j = gates[j] / denom * keep
        oh_c = jax.nn.one_hot(pos_t.astype(jnp.int32), cap,
                              dtype=probs.dtype)
        combine = combine + gate_j[:, None, None] * m[:, :, None] \
            * oh_c[:, None, :]
        offset = offset + jnp.sum(m, axis=0)
    dispatch = (combine > 0).astype(probs.dtype)
    return combine, dispatch, aux


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _ledger_a2a(x, axes, split_axis, concat_axis):
    """t_all_to_all whose BACKWARD also routes through the traced-
    collective shim: jax's built-in all_to_all transpose calls lax
    directly, which would leave the bwd dispatch/combine exchanges out
    of the comm ledger. The transpose of a (split s, concat c) a2a is
    the (split c, concat s) a2a."""
    return C.t_all_to_all(x, axes, split_axis, concat_axis, tiled=True)


def _ledger_a2a_fwd(x, axes, split_axis, concat_axis):
    return _ledger_a2a(x, axes, split_axis, concat_axis), None


def _ledger_a2a_bwd(axes, split_axis, concat_axis, _, g):
    return (C.t_all_to_all(g, axes, concat_axis, split_axis, tiled=True),)


_ledger_a2a.defvjp(_ledger_a2a_fwd, _ledger_a2a_bwd)


def _moe_forward(x2d, gate_w, w1, b1, w2, b2, axes, k, cap, act_fn,
                 ring=False):
    """Pure function: tokens [T, d] → ((output [T, d], aux loss),
    routing stats). The stats dict (per-expert load, routed/dropped
    slot counts) is non-differentiated telemetry — callers take it
    through ``jax.vjp(..., has_aux=True)``."""
    dt = x2d.dtype
    T = x2d.shape[0]
    logits = x2d.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    combine, dispatch, aux = _topk_dispatch(probs, k, cap)
    routed = jnp.float32(T * k)
    kept = jnp.sum(dispatch.astype(jnp.float32))
    stats = {
        "load": lax.stop_gradient(
            jnp.sum(dispatch, axis=(0, 2)).astype(jnp.float32)),
        "routed": routed,
        "dropped": lax.stop_gradient(jnp.maximum(routed - kept, 0.0)),
        "aux": lax.stop_gradient(aux.astype(jnp.float32)),
    }
    # dispatch: [T,E,C] x [T,d] -> [E,C,d]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x2d)
    if axes and ring:
        # fused path (ep_async_dispatch): dispatch-a2a + expert FFN +
        # combine-a2a as one chunked ppermute ring, the ICI exchange
        # hidden behind the per-block expert GEMMs
        from .....distributed import collective_matmul as cm

        out = cm.moe_a2a_ffn(expert_in, w1, b1, w2, b2, axes, act_fn)
        y = jnp.einsum("ecd,tec->td", out, combine.astype(dt))
        return (y, aux), stats
    if axes:
        # [E, C, d] -> [E/n, n*C, d]: each rank keeps its experts, slots
        # from every source rank ride ICI
        expert_in = _ledger_a2a(expert_in, axes, 0, 1)
    h = act_fn(jnp.einsum("ecd,edf->ecf", expert_in, w1)
               + b1[:, None, :].astype(dt))
    out = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :].astype(dt)
    if axes:
        out = _ledger_a2a(out, axes, 1, 0)
    y = jnp.einsum("ecd,tec->td", out, combine.astype(dt))
    return (y, aux), stats


def _extract_expert_weights(experts: List[Layer]):
    """Stack weights from a list of uniform FFN experts (reference
    ExpertLayer exposes htoh4/h4toh Linears; generic two-Linear experts
    also accepted)."""
    w1s, b1s, w2s, b2s = [], [], [], []
    for e in experts:
        if hasattr(e, "htoh4") and hasattr(e, "h4toh"):
            lin1, lin2 = e.htoh4, e.h4toh
        else:
            lins = [l for l in e.sublayers() if hasattr(l, "weight")
                    and getattr(l, "weight").ndim == 2]
            enforce(len(lins) == 2,
                    "stacked MoE needs uniform 2-linear experts (got "
                    f"{len(lins)} linears); use htoh4/h4toh naming or the "
                    "d_hidden constructor form")
            lin1, lin2 = lins
        w1s.append(np.asarray(lin1.weight._value))
        b1s.append(np.asarray(lin1.bias._value) if lin1.bias is not None
                   else np.zeros(lin1.weight.shape[1], "float32"))
        w2s.append(np.asarray(lin2.weight._value))
        b2s.append(np.asarray(lin2.bias._value) if lin2.bias is not None
                   else np.zeros(lin2.weight.shape[1], "float32"))
    return (np.stack(w1s), np.stack(b1s), np.stack(w2s), np.stack(b2s))


class MoELayer(Layer):
    """MoE layer (reference moe_layer.py:263 signature kept where it maps).

    Two construction forms::

        MoELayer(d_model, experts=[ExpertLayer(...), ...], gate=GShardGate(...))
        MoELayer(d_model, d_hidden=2048, num_experts=8, gate="gshard")

    ``group`` is the expert-parallel group (reference ``moe_group``);
    defaults to the fleet 'ep' group when ``ep_degree > 1`` (expert
    parallelism as a first-class hybrid axis), else to the dp group —
    the legacy "experts over dp" deployment. Stacked expert params are
    sharded over it on dim 0.
    """

    def __init__(self, d_model: int, experts=None, gate=None,
                 moe_group=None, mp_group=None, recompute_interval: int = 0,
                 d_hidden: Optional[int] = None,
                 num_experts: Optional[int] = None, group=None,
                 activation=None, **kw):
        super().__init__()
        if isinstance(experts, int) and d_hidden is None:
            d_hidden, experts = experts, None
        self.d_model = d_model
        group = group if group is not None else moe_group
        if group is False:  # explicit opt-out of expert parallelism
            group = None
        elif group is None:
            from .....distributed import fleet as _fleet

            hcg = _fleet.get_hybrid_communicate_group()
            if hcg is not None and \
                    hcg.get_expert_parallel_world_size() > 1:
                group = hcg.get_expert_parallel_group()
            elif hcg is not None and \
                    hcg.get_data_parallel_world_size() > 1:
                group = hcg.get_data_parallel_group()
        self._group = group
        self.world_size = group.nranks if group is not None else 1

        if experts is not None:
            experts = list(experts)
            num_experts = len(experts)
            w1, b1, w2, b2 = _extract_expert_weights(experts)
            d_hidden = w1.shape[2]
        enforce(num_experts is not None and d_hidden is not None,
                "need experts list or (d_hidden, num_experts)")
        enforce(num_experts % self.world_size == 0,
                f"num_experts {num_experts} must divide expert-parallel "
                f"degree {self.world_size}")
        self.num_experts = num_experts
        self.d_hidden = d_hidden

        if isinstance(gate, BaseGate):
            self.gate = gate
        else:
            name = gate or "gshard"
            cls = {"gshard": GShardGate, "switch": SwitchGate,
                   "naive": NaiveGate}[name]
            self.gate = cls(d_model, num_experts)

        if experts is not None:
            from .....nn import initializer as I

            self.w1 = self.create_parameter(
                w1.shape, default_initializer=I.Assign(w1))
            self.b1 = self.create_parameter(
                b1.shape, default_initializer=I.Assign(b1), is_bias=True)
            self.w2 = self.create_parameter(
                w2.shape, default_initializer=I.Assign(w2))
            self.b2 = self.create_parameter(
                b2.shape, default_initializer=I.Assign(b2), is_bias=True)
        else:
            E, d, h = num_experts, d_model, d_hidden
            self.w1 = self.create_parameter((E, d, h))
            self.b1 = self.create_parameter((E, h), is_bias=True)
            self.w2 = self.create_parameter((E, h, d))
            self.b2 = self.create_parameter((E, d), is_bias=True)
        if self.world_size > 1 and self._group is not None:
            axes = self._group.axis_names
            for p, nd in ((self.w1, 3), (self.b1, 2), (self.w2, 3),
                          (self.b2, 2)):
                p.dist_attr = P(*((axes,) + (None,) * (nd - 1)))
                p.is_distributed = True
        self._act = activation or jax.nn.gelu
        self.aux_loss = None

    def _capacity(self, T: int) -> int:
        cf = self.gate.capacity_factor
        if cf is None:
            return T  # naive gate: no token dropped
        raw = max(1, int(math.ceil(self.gate.top_k * cf * T
                                   / self.num_experts)))
        # bucket C onto the serving compile lattice (core/bucketing):
        # token-count / capacity-factor jitter lands on a handful of
        # power-of-two capacities instead of minting a new XLA program
        # per value. Rounding UP only ever keeps more tokens (effective
        # capacity factor >= requested); a cap above T is dead slots
        # (each expert queues at most T tokens), so clamp there.
        from .....core.bucketing import bucket

        return min(bucket(raw, lo=1), T)

    def forward(self, x: Tensor) -> Tensor:
        shape = list(x.shape)
        enforce(shape[-1] == self.d_model,
                f"last dim {shape[-1]} != d_model {self.d_model}")
        T = int(np.prod(shape[:-1]))
        cap = self._capacity(T)
        axes = (self._group.axis_names
                if self.world_size > 1 and C.in_spmd_region()
                and self._group is not None else ())

        from .....distributed import collective_matmul as _cm

        ring = bool(axes) and _cm.moe_overlap_available(axes)
        x2d = x._value.reshape(T, self.d_model)
        ins = (x2d, self.gate.weight._value, self.w1._value, self.b1._value,
               self.w2._value, self.b2._value)

        def pure(*vals):
            return _moe_forward(*vals, axes=axes, k=self.gate.top_k,
                                cap=cap, act_fn=self._act, ring=ring)

        in_tensors = [x, self.gate.weight, self.w1, self.b1, self.w2,
                      self.b2]
        need_grad = _engine.is_grad_enabled() and any(
            not t.stop_gradient for t in in_tensors)
        if need_grad:
            (y2d, aux), vjp_fn, stats = jax.vjp(pure, *ins, has_aux=True)
        else:  # inference: skip the linearization + residuals entirely
            (y2d, aux), stats = pure(*ins)
        _moestats.record(stats)
        y = Tensor(y2d.reshape(shape), stop_gradient=True)
        aux_t = Tensor(aux, stop_gradient=True)
        if need_grad:
            y.stop_gradient = aux_t.stop_gradient = False

            def bwd(gy, gaux):
                grads = vjp_fn((gy.reshape(T, self.d_model), gaux))
                # x's grad back to the caller's [..., d] layout
                return (grads[0].reshape(shape),) + tuple(grads[1:])

            _engine.record_custom("moe_layer", bwd, in_tensors,
                                  [y, aux_t], (y._value, aux_t._value))
        self.gate.set_loss(aux_t)
        self.aux_loss = aux_t
        return y

    def extra_repr(self):
        return (f"d={self.d_model}, h={self.d_hidden}, "
                f"E={self.num_experts}, ep={self.world_size}, "
                f"gate={type(self.gate).__name__}")
