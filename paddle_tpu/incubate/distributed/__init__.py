from . import models  # noqa: F401

__all__ = ["models"]
