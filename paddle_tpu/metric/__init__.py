"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


def accuracy(input, label, k=1):
    """Top-k accuracy (paddle.metric.accuracy)."""
    logits = _np(input)
    lbl = _np(label).reshape(-1)
    topk = np.argsort(-logits, axis=-1)[:, :k]
    correct = (topk == lbl[:, None]).any(axis=1)
    from .. import to_tensor

    return to_tensor(np.asarray(correct.mean(), dtype=np.float32))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self._name = name
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        predn = _np(pred)
        lbl = _np(label).reshape(predn.shape[0], -1)[:, 0]
        maxk = max(self.topk)
        topk_idx = np.argsort(-predn, axis=-1)[:, :maxk]
        correct = topk_idx == lbl[:, None]
        return correct

    def update(self, correct):
        correct = _np(correct)
        for i, k in enumerate(self.topk):
            self.total[i] += correct[:, :k].any(axis=1).sum()
            self.count[i] += correct.shape[0]
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bins = (pos_prob * self.num_thresholds).astype(np.int64)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        auc = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            auc += self._stat_neg[i] * (pos + self._stat_pos[i] / 2)
            pos += self._stat_pos[i]
            neg += self._stat_neg[i]
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name
