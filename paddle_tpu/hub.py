"""paddle.hub analog (reference: python/paddle/hub.py — loads
github/gitee-hosted hubconf.py entrypoints). Network fetch is
unavailable in this environment; local-directory sources work.
"""
from __future__ import annotations

import importlib.util
import os

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_local(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _entrypoints(mod):
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    if source != "local":
        raise NotImplementedError(
            "only source='local' is supported in this build (no egress)")
    return _entrypoints(_load_local(repo_dir))


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    if source != "local":
        raise NotImplementedError(
            "only source='local' is supported in this build (no egress)")
    fn = getattr(_load_local(repo_dir), model)
    return fn.__doc__


def load(repo_dir, model, *args, source="local", force_reload=False,
         **kwargs):
    if source != "local":
        raise NotImplementedError(
            "only source='local' is supported in this build (no egress)")
    mod = _load_local(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"entrypoint {model!r} not found; available: "
                         f"{_entrypoints(mod)}")
    return getattr(mod, model)(*args, **kwargs)
