"""Profiler (paddle.profiler analog).

(reference: python/paddle/profiler/profiler.py:79,99 — Profiler with
states/targets, export_chrome_tracing:215, RecordEvent host events,
profiler_statistic.py summaries; C++ host tracer
fluid/platform/profiler/host_tracer.cc + CUPTI cuda_tracer.)

TPU-native: the device side is the XLA/TPU profiler (xplane) reached
through ``jax.profiler`` — traces open in TensorBoard/Perfetto, covering
what CUPTI covered. The host side is a lightweight in-process event
recorder (RecordEvent) feeding ``summary()`` and the chrome-trace
exporter, the host_tracer role.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, List, Optional, Tuple

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "export_chrome_tracing", "make_scheduler", "load_profiler_result"]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


# event rows: (name, t0, t1, category, thread-id, thread-name). The
# thread id feeds the chrome exporter's `tid` so ServingEngine worker
# threads and the watchdog monitor thread separate into lanes.
_events: List[Tuple[str, float, float, str, int, str]] = []
_events_lock = threading.Lock()
_active = 0


def _append_event(name: str, t0: float, t1: float, cat: str):
    th = threading.current_thread()
    with _events_lock:
        _events.append((name, t0, t1, cat, th.ident or 0, th.name))


class RecordEvent:
    """Host-side named range (reference profiler/utils.py RecordEvent)."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter()

    def end(self):
        if self._t0 is None or not _active:
            return
        _append_event(self.name, self._t0, time.perf_counter(),
                      self.event_type)
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


@contextlib.contextmanager
def _op_record(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        # same `_active` gate as RecordEvent.end: an unstarted (or
        # already-stopped) profiler must not grow the global event list
        if _active:
            _append_event(name, t0, time.perf_counter(), "Operator")


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """(reference profiler.py make_scheduler) step → state."""
    period = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback writing chrome://tracing json
    (reference profiler.py:215)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        fname = f"{worker_name or 'worker'}_{os.getpid()}.pt.trace.json"
        prof._export_chrome(os.path.join(dir_name, fname))

    return handler


class Profiler:
    """paddle.profiler.Profiler analog.

    ``timer_only=True`` records host events only; otherwise the XLA/TPU
    device trace runs too (``jax.profiler``), written to ``log_dir`` for
    TensorBoard. ``scheduler`` is (start, end) step bounds or a
    make_scheduler callable.
    """

    def __init__(self, *, targets=None, scheduler=None,
                 on_trace_ready=None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory: bool = False,
                 log_dir: str = "./profiler_log"):
        self.timer_only = timer_only
        self.log_dir = log_dir
        self.on_trace_ready = on_trace_ready
        if isinstance(scheduler, tuple):
            lo, hi = scheduler
            scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo,
                                       repeat=1)
        self.scheduler = scheduler
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._device_tracing = False
        self._step_times: List[float] = []
        # (interval seconds, samples) pairs from step(num_samples=...)
        self._samples: List[Tuple[float, float]] = []
        self._last_step_t = None

    # -- lifecycle ------------------------------------------------------
    def start(self):
        global _active
        _active += 1
        if _active == 1:
            # only the OUTERMOST profiler resets the global recorder: a
            # nested start must neither clear the outer run's events nor
            # (on its stop) tear the dispatch hook out from under it
            with _events_lock:
                _events.clear()
            from ..core import dispatch as _dispatch

            _dispatch._profile_hook = _op_record
        self._state = (self.scheduler(self.step_num)
                       if self.scheduler else ProfilerState.RECORD)
        self._maybe_device(True)
        self._last_step_t = time.perf_counter()

    def stop(self):
        global _active
        self._maybe_device(False)
        _active = max(0, _active - 1)
        if _active == 0:
            from ..core import dispatch as _dispatch

            _dispatch._profile_hook = None
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def _maybe_device(self, start: bool):
        if self.timer_only:
            return
        try:
            import jax

            if start and not self._device_tracing and \
                    self._state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN):
                jax.profiler.start_trace(self.log_dir)
                self._device_tracing = True
            elif not start and self._device_tracing:
                jax.profiler.stop_trace()
                self._device_tracing = False
        except Exception:
            self._device_tracing = False

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            dur = now - self._last_step_t
            self._step_times.append(dur)
            if num_samples:
                # throughput accounting (reference profiler.py ips):
                # num_samples processed over the interval just ended
                self._samples.append((dur, float(num_samples)))
        self._last_step_t = now
        self.step_num += 1
        if self.scheduler:
            new = self.scheduler(self.step_num)
            if new != self._state:
                old, self._state = self._state, new
                if new in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
                    self._maybe_device(True)
                elif old in (ProfilerState.RECORD,
                             ProfilerState.RECORD_AND_RETURN):
                    self._maybe_device(False)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- reporting ------------------------------------------------------
    def summary(self, sorted_by=None, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms") -> str:
        unit = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        agg = {}
        with _events_lock:
            for name, t0, t1, *_ in _events:
                tot, cnt = agg.get(name, (0.0, 0))
                agg[name] = (tot + (t1 - t0), cnt + 1)
        lines = [f"{'Name':<40} {'Calls':>8} {'Total(' + time_unit + ')':>14}"
                 f" {'Avg(' + time_unit + ')':>12}"]
        for name, (tot, cnt) in sorted(agg.items(),
                                       key=lambda kv: -kv[1][0]):
            lines.append(f"{name[:40]:<40} {cnt:>8} {tot * unit:>14.3f} "
                         f"{tot * unit / cnt:>12.3f}")
        if self._step_times:
            import numpy as np

            st = np.asarray(self._step_times)
            lines.append(f"steps: {len(st)}  avg "
                         f"{st.mean() * unit:.3f}{time_unit}  p50 "
                         f"{np.percentile(st, 50) * unit:.3f}  p99 "
                         f"{np.percentile(st, 99) * unit:.3f}")
        if self._samples:
            tot_t = sum(d for d, _ in self._samples)
            tot_n = sum(n for _, n in self._samples)
            ips = tot_n / tot_t if tot_t > 0 else 0.0
            lines.append(f"throughput: {ips:.2f} ips "
                         f"({int(tot_n)} samples / {tot_t:.3f}s)")
        out = "\n".join(lines)
        print(out)
        return out

    def _export_chrome(self, path: str):
        with _events_lock:
            evs = list(_events)
        base = min((e[1] for e in evs), default=0.0)
        pid = os.getpid()
        events = []
        lanes = {}                  # tid -> thread name (first seen)
        for name, t0, t1, cat, tid, tname in evs:
            lanes.setdefault(tid, tname)
            events.append(
                {"name": name, "ph": "X", "pid": pid, "tid": tid,
                 "ts": (t0 - base) * 1e6, "dur": (t1 - t0) * 1e6,
                 "cat": cat})
        # chrome://tracing / Perfetto label each lane from thread_name
        # metadata — serving workers and the watchdog monitor get their
        # python thread names
        for tid, tname in lanes.items():
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    export = _export_chrome


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)
