"""Short-time Fourier transforms (paddle.signal analog).

(reference: python/paddle/signal.py — frame/overlap_add over phi
frame/overlap_add kernels + fft_r2c/fft_c2c/fft_c2r. Here framing is a
gather (XLA lowers it to a strided window read), the DFTs are XLA's
native FFT HLO, and overlap-add is a scatter-add — all differentiable
and fusible; no dynloaded FFT library.)
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.dispatch import def_op
from .core.enforce import enforce

__all__ = ["stft", "istft"]


def _n_frames(seq_len, frame_length, hop_length):
    return 1 + (seq_len - frame_length) // hop_length


@def_op("frame")
def frame(x, frame_length, hop_length, axis=-1):
    """Slice ``x`` into overlapping frames along ``axis`` (last or first).

    Output adds a ``frame_length`` axis next to ``axis``:
    axis=-1 -> [..., frame_length, num_frames]; axis=0 ->
    [num_frames, frame_length, ...].
    """
    frame_length = int(frame_length)
    hop_length = int(hop_length)
    axis = int(axis)
    enforce(hop_length > 0, lambda: f"hop_length must be > 0, got {hop_length}")
    enforce(axis in (-1, 0, x.ndim - 1),
            lambda: "frame only supports the first or last axis")
    seq_len = x.shape[axis]
    enforce(frame_length <= seq_len,
            lambda: f"frame_length ({frame_length}) > sequence length "
                    f"({seq_len})")
    n = _n_frames(seq_len, frame_length, hop_length)
    # [n, frame_length] start-offset + in-frame index gather
    idx = (np.arange(n)[:, None] * hop_length
           + np.arange(frame_length)[None, :])
    if axis == 0:  # first-axis framing (also the 1-D axis=0 case)
        return jnp.take(x, jnp.asarray(idx), axis=0)  # [n, frame_length, ...]
    out = jnp.take(x, jnp.asarray(idx), axis=x.ndim - 1)
    # [..., n, frame_length] -> [..., frame_length, n]
    return jnp.swapaxes(out, -1, -2)


@def_op("overlap_add")
def overlap_add(x, hop_length, axis=-1):
    """Inverse of ``frame``: scatter-add overlapping frames.

    axis=-1 expects [..., frame_length, num_frames]; axis=0 expects
    [num_frames, frame_length, ...].
    """
    hop_length = int(hop_length)
    axis = int(axis)
    enforce(hop_length > 0, lambda: f"hop_length must be > 0, got {hop_length}")
    enforce(axis in (-1, 0, x.ndim - 1),
            lambda: "overlap_add only supports the first or last axis")
    first = axis == 0 and x.ndim != 1
    if first:
        # [n, frame_length, ...] -> [..., frame_length, n]
        x = jnp.moveaxis(jnp.moveaxis(x, 0, -1), 0, -2)
    frame_length, n = x.shape[-2], x.shape[-1]
    seq_len = (n - 1) * hop_length + frame_length
    idx = (np.arange(n)[None, :] * hop_length
           + np.arange(frame_length)[:, None])  # [frame_length, n]
    flat = x.reshape(x.shape[:-2] + (-1,))
    out = jnp.zeros(x.shape[:-2] + (seq_len,), x.dtype)
    out = out.at[..., jnp.asarray(idx.reshape(-1))].add(flat)
    if first:
        out = jnp.moveaxis(out, -1, 0)
    return out


@def_op("stft")
def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True):
    """Short-time Fourier transform: [..., T] -> [..., F, num_frames]."""
    n_fft = int(n_fft)
    hop_length = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else n_fft
    enforce(x.ndim in (1, 2),
            lambda: f"stft expects a 1-D or 2-D input, got rank {x.ndim}")
    enforce(win_length <= n_fft,
            lambda: f"win_length ({win_length}) > n_fft ({n_fft})")
    enforce(not (onesided and (jnp.iscomplexobj(x) or (
        window is not None and jnp.iscomplexobj(jnp.asarray(window))))),
            lambda: "onesided must be False for a complex input or window: "
                    "complex signals have no hermitian symmetry to exploit")
    squeeze = x.ndim == 1
    if squeeze:
        x = x[None]
    if window is None:
        win = jnp.ones((win_length,), jnp.result_type(x.dtype, jnp.float32))
    else:
        win = jnp.asarray(window)
        enforce(win.shape == (win_length,),
                lambda: f"window must have shape ({win_length},), got "
                        f"{win.shape}")
    if win_length < n_fft:  # center-pad the window to n_fft
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    if center:
        x = jnp.pad(x, ((0, 0), (n_fft // 2, n_fft // 2)), mode=pad_mode)
    frames = frame.raw(x, n_fft, hop_length, -1)     # [B, n_fft, n]
    frames = frames * win[None, :, None]
    if jnp.iscomplexobj(frames):  # onesided=False enforced above
        out = jnp.fft.fft(frames, axis=-2)
    elif onesided:
        out = jnp.fft.rfft(frames, axis=-2)
    else:
        out = jnp.fft.fft(frames.astype(jnp.complex64), axis=-2)
    if normalized:
        out = out / jnp.sqrt(jnp.asarray(n_fft, out.real.dtype))
    return out[0] if squeeze else out


@def_op("istft")
def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False):
    """Inverse STFT (least-squares / NOLA estimate)."""
    n_fft = int(n_fft)
    hop_length = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else n_fft
    enforce(x.ndim in (2, 3),
            lambda: f"istft expects a 2-D or 3-D input, got rank {x.ndim}")
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    n_freq = n_fft // 2 + 1 if onesided else n_fft
    enforce(x.shape[-2] == n_freq,
            lambda: f"expected {n_freq} frequency rows, got {x.shape[-2]}")
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = jnp.asarray(window)
        enforce(win.shape == (win_length,),
                lambda: f"window must have shape ({win_length},), got "
                        f"{win.shape}")
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    if normalized:
        x = x * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    x = jnp.swapaxes(x, -1, -2)                       # [B, n, F]
    if onesided and not return_complex:
        frames = jnp.fft.irfft(x, n=n_fft, axis=-1)   # real path
    else:
        full = x
        if onesided:  # rebuild hermitian half before the complex IDFT
            mid = jnp.conj(full[..., 1:n_fft - n_fft // 2][..., ::-1])
            full = jnp.concatenate([full, mid], axis=-1)
        frames = jnp.fft.ifft(full, axis=-1)
        if not return_complex:
            frames = frames.real
    frames = frames * win                              # [B, n, n_fft]
    num = overlap_add.raw(jnp.swapaxes(frames, -1, -2), hop_length, -1)
    den = overlap_add.raw(
        jnp.broadcast_to((win * win)[:, None],
                         (n_fft, frames.shape[1])), hop_length, -1)
    out = num / jnp.maximum(den, 1e-11)
    if center:
        out = out[..., n_fft // 2:]
        if length is None:
            out = out[..., : out.shape[-1] - n_fft // 2]
    if length is not None:
        out = out[..., : int(length)]
    return out[0] if squeeze else out
