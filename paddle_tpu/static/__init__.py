"""Static-graph compatibility surface (reference: python/paddle/static/).

The reference's static mode builds a ProgramDesc executed by the C++
interpreter (SURVEY.md §3.4); here "static" IS jax.jit tracing, so this
module provides the declarative pieces programs are written against —
InputSpec for signatures — plus thin Program/Executor shims that map the
classic ``paddle.static`` training-script shape onto traced execution.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.dtype import convert_dtype

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "Executor",
           "name_scope"]


class InputSpec:
    """Signature element (reference: paddle/static/input.py InputSpec).
    ``None`` dims become symbolic (dynamic batch) on export."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None, stop_gradient: bool = True):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), str(tensor.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


class Program:
    """Placeholder program object (graphs are implicit under jit)."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self

    def clone(self, for_test: bool = False):
        return self


_main = Program()
_startup = Program()


def default_main_program() -> Program:
    return _main


def default_startup_program() -> Program:
    return _startup


import contextlib  # noqa: E402


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    yield


@contextlib.contextmanager
def name_scope(prefix: str = ""):
    yield


class Executor:
    """Minimal Executor shim (reference base/executor.py:1162): ``run``
    calls a compiled callable registered as the fetch target."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kw):
        if callable(program):
            out = program(**(feed or {}))
            return [np.asarray(getattr(o, "_value", o))
                    for o in (out if isinstance(out, (list, tuple))
                              else [out])]
        raise NotImplementedError(
            "static Program execution is implicit under jit in this "
            "framework; pass a compiled callable (paddle.jit.to_static) "
            "or use the eager/hapi APIs")
