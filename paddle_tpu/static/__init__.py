"""Static-graph mode (reference: python/paddle/static/).

The reference's static mode builds a ProgramDesc run by the C++
interpreter (SURVEY.md §3.4: Executor.run → StandaloneExecutor →
ProgramInterpreter). TPU redesign: a ``Program`` here is a recorded op
list — ops called on symbolic ``Variable``s (from ``static.data``)
append ``OpNode``s through the SAME dispatch chokepoint eager uses
(core/dispatch.apply), and ``Executor.run`` replays the graph with real
feed arrays through the eager engine, so autograd/AMP/profiler hooks
all apply. ``minimize`` records the train objective; the replay then
runs loss.backward() + optimizer.step() — both already fused/jitted —
giving the classic declare-then-run paddle.static workflow on XLA.

Parameters initialize eagerly at layer construction (the reference's
startup program runs initializer ops; here ``exe.run(startup)`` is a
documented no-op).
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.dtype import convert_dtype
from ..core.enforce import enforce
from ..tensor import Parameter, Tensor, to_tensor

__all__ = ["InputSpec", "Program", "Variable", "data",
           "default_main_program", "default_startup_program",
           "program_guard", "Executor", "name_scope", "CompiledProgram",
           "nn"]

from . import nn  # noqa: E402,F401  (lax-backed control flow: cond/while_loop/case/switch_case)


class InputSpec:
    """Signature element (reference: paddle/static/input.py InputSpec).
    ``None`` dims become symbolic (dynamic batch) on export."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None, stop_gradient: bool = True):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), str(tensor.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


class Variable(Tensor):
    """Symbolic tensor living in a Program (reference: base/framework.py
    Variable). Has shape/dtype metadata but no storage; any op touching
    one records into the Program instead of executing."""

    _is_static_var = True

    def __init__(self, program: "Program", shape, dtype, name: str,
                 stop_gradient: bool = True):
        super().__init__(None, stop_gradient=stop_gradient, name=name)
        self._program = program
        self._shape = tuple(shape)
        self._dtype = convert_dtype(dtype)
        from ..core import dispatch as _dispatch

        _dispatch._static_used[0] = True

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no value at build time; fetch it "
            f"through Executor.run(fetch_list=[...])")

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={list(self._shape)}, "
                f"dtype={self._dtype})")


class OpNode:
    __slots__ = ("opdef", "args", "kwargs", "outputs")

    def __init__(self, opdef, args, kwargs, outputs):
        self.opdef = opdef
        self.args = args
        self.kwargs = kwargs
        self.outputs = outputs  # list[Variable]


class Program:
    """Recorded op graph (reference: base/framework.py Program /
    ProgramDesc)."""

    def __init__(self):
        self._nodes: List[OpNode] = []
        self._feeds: Dict[str, Variable] = {}
        self._var_count = 0
        # set by Optimizer.minimize: (loss Variable, optimizer)
        self._train_objective = None

    # -- recording ------------------------------------------------------
    def _new_var(self, shape, dtype, stop_gradient=True, name=None):
        self._var_count += 1
        name = name or f"_generated_var_{self._var_count}"
        return Variable(self, shape, dtype, name,
                        stop_gradient=stop_gradient)

    def _record(self, opdef, args, kwargs):
        """Append an op node; infer output metadata via jax.eval_shape
        (falls back to unknown shape — the replay is the ground truth)."""
        import jax

        def as_spec(v):
            if isinstance(v, Variable):
                shape = tuple(1 if d is None or d < 0 else d
                              for d in v._shape)
                return jax.ShapeDtypeStruct(shape, v._dtype)
            if isinstance(v, Tensor):
                return v._value
            return v

        try:
            spec_args = [as_spec(a) for a in args]
            spec_kwargs = {k: as_spec(v) for k, v in kwargs.items()}
            metas = jax.eval_shape(opdef.fn, *spec_args, **spec_kwargs)
        except Exception as e:
            # a wrong single-output guess would silently truncate
            # multi-output ops at replay; fail loudly at build time
            raise RuntimeError(
                f"static mode could not infer output metadata for op "
                f"{opdef.name!r} (ops with data-dependent host logic "
                f"cannot be recorded): {type(e).__name__}: {e}") from e
        multi = isinstance(metas, (tuple, list))
        metas_list = list(metas) if multi else [metas]
        sg = not any(isinstance(v, (Variable, Tensor))
                     and not v.stop_gradient
                     for v in list(args) + list(kwargs.values()))
        outs = [self._new_var(m.shape, m.dtype, stop_gradient=sg)
                for m in metas_list]
        self._nodes.append(OpNode(opdef, args, kwargs, outs))
        return tuple(outs) if multi else outs[0]

    # -- paddle API surface --------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test: bool = False):
        p = Program()
        p._nodes = list(self._nodes)
        p._feeds = dict(self._feeds)
        p._var_count = self._var_count
        if not for_test:
            p._train_objective = self._train_objective
        return p

    def __repr__(self):
        ops = ", ".join(n.opdef.name for n in self._nodes[:8])
        more = "..." if len(self._nodes) > 8 else ""
        return (f"Program({len(self._nodes)} ops: {ops}{more}; "
                f"feeds={list(self._feeds)})")


_main = Program()
_startup = Program()
_guard_stack: List[Program] = []


def default_main_program() -> Program:
    return _main


def default_startup_program() -> Program:
    return _startup


def current_program() -> Program:
    return _guard_stack[-1] if _guard_stack else _main


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _guard_stack.append(main_program)
    try:
        yield
    finally:
        _guard_stack.pop()


@contextlib.contextmanager
def name_scope(prefix: str = ""):
    yield


def data(name: str, shape, dtype="float32", lod_level=0):
    """Declare a feed Variable (reference: paddle/static/input.py
    data)."""
    prog = current_program()
    var = Variable(prog, tuple(shape), dtype, name, stop_gradient=True)
    prog._feeds[name] = var
    return var


# static/nn control-flow branches set this while they run: a symbolic
# Variable reaching dispatch inside a branch would otherwise silently
# record the branch body into the live Program (region-less op list)
# and then crash opaquely on the Variable's absent value
_in_control_flow = [0]


def record_op(opdef, args, kwargs):
    """Called from core.dispatch.apply when an input is symbolic.

    Records into the active ``program_guard`` program when one is open
    (so ops appended after ``clone()`` land in the clone, matching the
    reference's guard semantics); otherwise into the inputs' program,
    which must then be unambiguous."""
    enforce(not _in_control_flow[0],
            "a static-graph Variable reached a static.nn control-flow "
            "branch/body: cond/while_loop/case/switch_case cannot be "
            "recorded into a declare-then-run Program (the replayed op "
            "list has no sub-block regions). Run the model under "
            "paddle.jit.to_static instead, where they lower to lax "
            "control-flow HLOs.")
    if _guard_stack:
        return _guard_stack[-1]._record(opdef, args, kwargs)
    progs = {v._program for v in list(args) + list(kwargs.values())
             if isinstance(v, Variable)}
    enforce(len(progs) == 1,
            "op mixes Variables from different Programs (open a "
            "program_guard to choose the recording target)")
    return next(iter(progs))._record(opdef, args, kwargs)


class Executor:
    """Replays a Program with real feed values through the eager engine
    (reference: base/executor.py:1162 — there an instruction interpreter;
    here each replayed op goes through the jitted dispatch path, and the
    recorded train objective runs backward + the fused optimizer step)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kw):
        if isinstance(program, CompiledProgram):
            return program.run(feed or {}, fetch_list or [])
        if callable(program) and not isinstance(program, Program):
            out = program(**(feed or {}))
            return [np.asarray(getattr(o, "_value", o))
                    for o in (out if isinstance(out, (list, tuple))
                              else [out])]
        if program is None:
            program = default_main_program()
        if program is _startup or not program._nodes:
            return []  # startup: parameters initialized eagerly
        feed = feed or {}
        env: Dict[int, Tensor] = {}
        for name, var in program._feeds.items():
            enforce(name in feed,
                    lambda: f"missing feed '{name}' "
                            f"(declared via static.data)")
            val = to_tensor(np.asarray(feed[name],
                                       dtype=str(var._dtype)))
            env[id(var)] = val

        train = program._train_objective

        def resolve(v):
            if isinstance(v, Variable):
                enforce(id(v) in env,
                        lambda: f"Variable {v.name!r} used before "
                                f"definition in the program")
                return env[id(v)]
            return v

        from ..autograd import engine as _engine
        from ..core import dispatch as _dispatch

        loss_tensor = None
        with _engine.enable_grad() if train else contextlib.nullcontext():
            for node in program._nodes:
                r_args = [resolve(a) for a in node.args]
                r_kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
                out = _dispatch.apply(node.opdef, tuple(r_args), r_kwargs)
                outs = list(out) if isinstance(out, tuple) else [out]
                for var, val in zip(node.outputs, outs):
                    env[id(var)] = val
                    if train and var is train[0]:
                        loss_tensor = val

        if train is not None:
            loss_var, optimizer = train
            enforce(loss_tensor is not None,
                    "minimize() loss was not produced by this program")
            loss_tensor.backward()
            optimizer.step()
            optimizer.clear_grad()

        results = []
        for f in fetch_list or []:
            t = resolve(f) if isinstance(f, Variable) else f
            results.append(np.asarray(t._value if isinstance(t, Tensor)
                                      else t))
        return results

    def close(self):
        pass


class CompiledProgram:
    """Whole-graph compiled replay for inference programs (no train
    objective): the node list traces into ONE jitted XLA program, keyed
    on feed shapes (reference: the build_strategy/ParallelExecutor
    surface, subsumed by jax.jit)."""

    def __init__(self, program: Program, build_strategy=None):
        enforce(program._train_objective is None,
                "CompiledProgram compiles inference programs; training "
                "replays run through Executor (backward needs the tape)")
        self._program = program
        # eager tensors (parameters/constants) captured by the graph, in
        # deterministic order — passed as traced ARGUMENTS so weight
        # updates after compilation are picked up, never baked in
        consts: Dict[int, Tensor] = {}
        for node in program._nodes:
            for v in list(node.args) + list(node.kwargs.values()):
                if isinstance(v, Tensor) and not isinstance(v, Variable):
                    consts.setdefault(id(v), v)
        self._const_tensors = list(consts.values())
        self._cache: Dict[Any, Any] = {}

    def _build(self, feed_names, fetch_ids):
        import jax

        prog = self._program
        const_ids = [id(t) for t in self._const_tensors]

        def fn(feed_values, const_values):
            env = dict(zip(const_ids, const_values))
            for name, val in zip(feed_names, feed_values):
                env[id(prog._feeds[name])] = val

            def resolve(v):
                if isinstance(v, Variable):
                    return env[id(v)]
                if isinstance(v, Tensor):
                    return env[id(v)]
                return v

            for node in prog._nodes:
                out = node.opdef.fn(*[resolve(a) for a in node.args],
                                    **{k: resolve(v)
                                       for k, v in node.kwargs.items()})
                outs = list(out) if isinstance(out, tuple) else [out]
                for var, val in zip(node.outputs, outs):
                    env[id(var)] = val
            # only the fetched values become XLA outputs (DCE prunes the
            # rest of the graph)
            return [env[i] for i in fetch_ids]

        return jax.jit(fn)

    def run(self, feed: Dict[str, Any], fetch_list):
        feed_names = sorted(self._program._feeds)
        vals = [np.asarray(feed[n]) for n in feed_names]
        fetch_ids = tuple(id(f) for f in fetch_list)
        key = (tuple((v.shape, str(v.dtype)) for v in vals), fetch_ids)
        if key not in self._cache:
            self._cache[key] = self._build(feed_names, fetch_ids)
        consts = [t._value for t in self._const_tensors]
        outs = self._cache[key](vals, consts)
        return [np.asarray(o) for o in outs]
