"""Data-dependent control flow (reference: python/paddle/static/nn/
control_flow.py — cond:1166, While/while_loop:1380, case:2310,
switch_case:2517; capability also covered there by the SOT bytecode
tracer, python/paddle/jit/sot/).

TPU redesign: the reference lowers these to ConditionalBlock /
While ops interpreted by the C++ executor. Here they lower DIRECTLY to
``lax.cond`` / ``lax.while_loop`` / ``lax.switch`` — XLA's native
control-flow HLOs — so they work identically in eager execution (the
predicate is concrete and the branch just runs) and under
``paddle.jit.to_static`` tracing (the branch becomes a compiled HLO
region; this is what makes tensor-valued Python ``if``/``while`` —
which CANNOT trace — expressible).

Differentiability: ``cond`` IS differentiable (``lax.cond`` has a
reverse-mode rule, and so does the reference's cond) — when grad is
enabled, the branch closures' differentiable inputs are discovered via
a dispatch-level capture pass, both branches become pure functions of
those captured tensors, and the whole ``lax.cond`` is recorded on the
tape as one custom node whose backward is ``jax.vjp`` of the same cond.
``while_loop`` stays NON-differentiable (``lax.while_loop`` has no
reverse-mode rule) and raises loudly when a loop var requires grad;
``case``/``switch_case`` branch fns still run under ``no_grad``. XLA
requires both branches/iterations to carry identical structures,
shapes, and dtypes; mismatches raise with the offending leaf named.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ...core.enforce import enforce
from ...tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else jnp.asarray(x),
        tree, is_leaf=lambda x: isinstance(x, Tensor))


def _wrap(tree):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v, stop_gradient=True), tree)


def _no_program_recording(api, *values):
    """Program-recording mode replays a flat op list; control-flow
    REGIONS (sub-blocks) are not recordable there — gate loudly with
    the working alternative instead of an opaque AttributeError.
    Checks every LEAF (matching what _unwrap will touch); Variables
    captured in branch closures are caught by the _cf_guard sentinel
    that static.record_op consults."""
    for v in jax.tree_util.tree_leaves(
            list(values), is_leaf=lambda x: isinstance(x, Tensor)):
        if getattr(v, "_is_static_var", False):
            enforce(False,
                    f"static.nn.{api} cannot be recorded into a "
                    "declare-then-run Program (the replayed op list has "
                    "no sub-block regions). Run the model under "
                    "paddle.jit.to_static instead - cond/while_loop/"
                    "case/switch_case lower to lax control-flow HLOs "
                    "there (and in eager mode).")


import contextlib  # noqa: E402


@contextlib.contextmanager
def _cf_guard():
    """While a branch/body runs, a symbolic Variable reaching dispatch
    raises the clear static-mode message (see static.record_op)."""
    from .. import _in_control_flow

    _in_control_flow[0] += 1
    try:
        yield
    finally:
        _in_control_flow[0] -= 1


def _scalar_pred(pred, api):
    _no_program_recording(api, pred)
    pv = pred._value if isinstance(pred, Tensor) else jnp.asarray(pred)
    enforce(int(np.prod(pv.shape)) == 1,
            lambda: f"{api} predicate must have exactly one element, "
                    f"got shape {tuple(pv.shape)}")
    return pv.reshape(()).astype(bool)


def _run_branch(fn, api, args=()):
    """Run a user branch/body fn on wrapped Tensors, return the unwrapped
    value pytree (no_grad: see module doc)."""
    from ...autograd import no_grad

    with no_grad(), _cf_guard():
        out = fn(*_wrap(args)) if args else fn()
    return _unwrap(out)


def _check_match(a, b, api, names=("true_fn", "false_fn")):
    ta = jax.tree_util.tree_structure(a)
    tb = jax.tree_util.tree_structure(b)
    enforce(ta == tb,
            lambda: f"{api}: {names[0]} and {names[1]} must return the "
                    f"same structure, got {ta} vs {tb}")
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        enforce(la.shape == lb.shape and la.dtype == lb.dtype,
                lambda: f"{api}: branch outputs must match in shape and "
                        f"dtype (XLA control flow), got "
                        f"{la.shape}/{la.dtype} vs {lb.shape}/{lb.dtype}")


def _captured_inputs(fn, api):
    """Run ``fn`` once under ``no_grad`` with the dispatch-level input
    observer installed; returns the ordered unique EXTERNAL
    differentiable Tensors the closure consumes. (Inside the no_grad
    run every branch-internal intermediate is stop_gradient, so only
    the closure boundary reaches the observer.)"""
    from ...core import dispatch as _dispatch

    seen, order = set(), []

    def obs(t):
        if id(t) not in seen:
            seen.add(id(t))
            order.append(t)

    prev = _dispatch._input_observer
    _dispatch._input_observer = obs
    try:
        _run_branch(fn, api)
    finally:
        _dispatch._input_observer = prev
    return order


@contextlib.contextmanager
def _bound_values(tensors, vals):
    """Temporarily swap each captured Tensor's backing array, making a
    branch closure a pure function of ``vals`` (the functional-call
    trick of distributed.engine.bind_params)."""
    saved = [t._value for t in tensors]
    try:
        for t, v in zip(tensors, vals):
            t._value = v
        yield
    finally:
        for t, v in zip(tensors, saved):
            t._value = v


def _diff_cond(pv, true_fn, false_fn):
    """Differentiable cond: one lax.cond over the branches as pure
    functions of their captured tensors, recorded on the tape as a
    custom node whose backward is jax.vjp of the same cond (correct
    under BOTH loss.backward() and pure transforms)."""
    from ...autograd import engine as _engine

    caps = _captured_inputs(true_fn, "cond")
    cap_ids = {id(t) for t in caps}
    for t in _captured_inputs(false_fn, "cond"):
        if id(t) not in cap_ids:
            cap_ids.add(id(t))
            caps.append(t)
    if not caps:
        return NotImplemented          # nothing differentiable below

    td_box = []

    def branch(fn):
        def run(vals):
            with _bound_values(caps, list(vals)):
                out = _run_branch(fn, "cond")
            leaves, td = jax.tree_util.tree_flatten(out)
            td_box.append(td)
            return tuple(leaves)

        return run

    def cond_fn(*vals):
        return lax.cond(pv, branch(true_fn), branch(false_fn), vals)

    cap_vals = tuple(t._value for t in caps)
    out_leaves = cond_fn(*cap_vals)
    treedef = td_box[0]
    diff_idx = [i for i, v in enumerate(out_leaves)
                if jnp.issubdtype(v.dtype, jnp.inexact)]
    out_tensors = [Tensor(v, stop_gradient=i not in diff_idx)
                   for i, v in enumerate(out_leaves)]
    if diff_idx:
        def bwd(*gs):
            def diff_fn(*vals):
                leaves = cond_fn(*vals)
                return tuple(leaves[i] for i in diff_idx)

            _, vjp = jax.vjp(diff_fn, *cap_vals)
            grads = vjp(tuple(gs))
            return tuple(
                None if getattr(g, "dtype", None) == jax.dtypes.float0
                else g for g in grads)

        _engine.record_custom(
            "static_cond", bwd, list(caps),
            [out_tensors[i] for i in diff_idx],
            tuple(out_leaves[i] for i in diff_idx))
    return jax.tree_util.tree_unflatten(treedef, out_tensors)


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name=None,
         return_names=None):
    """Run ``true_fn()`` if ``pred`` else ``false_fn()`` — as a
    ``lax.cond`` HLO, so a TENSOR-VALUED predicate works under
    ``to_static`` tracing (reference: static/nn/control_flow.py:1166).
    Differentiable: gradients flow to tensors the branch closures
    capture (lax.cond supports reverse mode; see module doc).
    """
    enforce(true_fn is not None or false_fn is not None,
            "cond needs at least one of true_fn/false_fn")
    if true_fn is None or false_fn is None:
        # single-branch form returns nothing; only runnable with a
        # concrete predicate (a traced one needs both branches)
        pv = _scalar_pred(pred, "cond")
        enforce(not isinstance(pv, jax.core.Tracer),
                "cond with a single branch needs a concrete predicate; "
                "under to_static tracing pass BOTH true_fn and false_fn")
        if bool(pv) == (true_fn is not None):
            out = _run_branch(true_fn or false_fn, "cond")
            enforce(not jax.tree_util.tree_leaves(out),
                    "cond with a single branch cannot return tensors "
                    "(the missing branch has nothing to return)")
        return None
    pv = _scalar_pred(pred, "cond")

    # probe both branches once for structure/shape agreement (cheap at
    # trace time; gives the named error instead of an XLA type clash)
    ta = jax.eval_shape(lambda: _run_branch(true_fn, "cond"))
    fa = jax.eval_shape(lambda: _run_branch(false_fn, "cond"))
    _check_match(ta, fa, "cond")

    from ...autograd import engine as _engine

    if _engine.is_grad_enabled():
        out = _diff_cond(pv, true_fn, false_fn)
        if out is not NotImplemented:
            return out

    out = lax.cond(pv, lambda: _run_branch(true_fn, "cond"),
                   lambda: _run_branch(false_fn, "cond"))
    return _wrap(out)


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None) -> List:
    """``while cond(*vars): vars = body(*vars)`` as a
    ``lax.while_loop`` HLO (reference: static/nn/control_flow.py:1380).
    Loop-carried shapes/dtypes must be invariant across iterations.

    NOT differentiable (``lax.while_loop`` has no reverse-mode rule):
    a loop var that requires grad raises loudly instead of silently
    dropping the gradient — detach the inputs or restructure with
    ``cond``/masked ``where`` selects for trainable control flow."""
    enforce(len(loop_vars) > 0, "while_loop needs at least one loop var")
    _no_program_recording("while_loop", *loop_vars)
    from ...autograd import engine as _engine

    if _engine.is_grad_enabled():
        for i, t in enumerate(jax.tree_util.tree_leaves(
                list(loop_vars), is_leaf=lambda x: isinstance(x, Tensor))):
            enforce(not (isinstance(t, Tensor) and not t.stop_gradient),
                    f"static.nn.while_loop is not differentiable, but "
                    f"loop var {i} requires grad (stop_gradient=False): "
                    "lax.while_loop has no reverse-mode rule. Detach the "
                    "input (.detach() / stop_gradient=True), call under "
                    "paddle.no_grad(), or restructure with static.nn."
                    "cond / masked where selects (which ARE "
                    "differentiable).")
    init = tuple(_unwrap(list(loop_vars)))

    def c(vs):
        return _scalar_pred(Tensor(_cond_val(vs)), "while_loop")

    def _cond_val(vs):
        from ...autograd import no_grad

        with no_grad(), _cf_guard():
            out = cond(*_wrap(list(vs)))
        return out._value if isinstance(out, Tensor) else jnp.asarray(out)

    def b(vs):
        out = _run_branch(body, "while_loop", args=list(vs))
        out = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        enforce(len(out) == len(vs),
                lambda: f"while_loop body returned {len(out)} vars, "
                        f"expected {len(vs)}")
        for i, (o, v) in enumerate(zip(out, vs)):
            enforce(o.shape == v.shape and o.dtype == v.dtype,
                    lambda: f"while_loop var {i} changed "
                            f"shape/dtype {v.shape}/{v.dtype} -> "
                            f"{o.shape}/{o.dtype}; loop-carried values "
                            "must be invariant (XLA while)")
        return out

    out = lax.while_loop(c, b, init)
    return [Tensor(v, stop_gradient=True) for v in out]


def case(pred_fn_pairs: Sequence[Tuple[Any, Callable]],
         default: Optional[Callable] = None, name=None):
    """First pair whose pred is True runs; else ``default`` (reference:
    static/nn/control_flow.py:2310). Lowered as nested ``lax.cond``."""
    enforce(len(pred_fn_pairs) > 0, "case needs at least one (pred, fn)")
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
        enforce(len(pairs) > 0,
                "case without default needs >= 2 pairs (the last "
                "becomes the default, reference semantics)")

    shapes = [jax.eval_shape(lambda f=f: _run_branch(f, "case"))
              for _, f in pairs] + \
             [jax.eval_shape(lambda: _run_branch(default, "case"))]
    for s in shapes[1:]:
        _check_match(shapes[0], s, "case", ("branch 0", "a later branch"))

    def build(i):
        if i == len(pairs):
            return lambda: _run_branch(default, "case")
        pred, fn = pairs[i]
        pv = _scalar_pred(pred, "case")
        nxt = build(i + 1)
        return lambda: lax.cond(pv, lambda: _run_branch(fn, "case"), nxt)

    return _wrap(build(0)())


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name=None):
    """Run ``branch_fns[branch_index]`` as a ``lax.switch`` HLO
    (reference: static/nn/control_flow.py:2517). ``branch_fns`` is a
    list of fns, or (index, fn) pairs; out-of-range indices take
    ``default`` (appended as the last switch branch, clamp-mapped)."""
    _no_program_recording("switch_case", branch_index)
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    enforce(len(set(keys)) == len(keys),
            "switch_case branch indices must be unique")
    if default is None:
        default = fns[-1]

    shapes = [jax.eval_shape(lambda f=f: _run_branch(f, "switch_case"))
              for f in fns + [default]]
    for s in shapes[1:]:
        _check_match(shapes[0], s, "switch_case",
                     ("branch 0", "a later branch"))

    iv = branch_index._value if isinstance(branch_index, Tensor) \
        else jnp.asarray(branch_index)
    iv = iv.reshape(()).astype(jnp.int32)
    # map sparse keys -> dense positions; unmatched -> default (last)
    pos = len(fns)
    sel = jnp.asarray(pos, jnp.int32)
    for p, k in enumerate(keys):
        sel = jnp.where(iv == k, jnp.asarray(p, jnp.int32), sel)
    branches = [(lambda f=f: _run_branch(f, "switch_case"))
                for f in fns + [default]]
    return _wrap(lax.switch(sel, branches))
