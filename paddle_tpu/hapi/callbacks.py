"""hapi callbacks (reference: python/paddle/hapi/callbacks.py —
Callback/ProgBarLogger/ModelCheckpoint/EarlyStopping/LRScheduler)."""
from __future__ import annotations

import os
import sys
import time
from typing import Optional

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRSchedulerCallback", "config_callbacks"]


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks, model, params):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)


class ProgBarLogger(Callback):
    """Per-epoch progress logging (reference callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq: int = 10, verbose: int = 1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            msg = " - ".join(f"{k}: {v:.4f}" for k, v in
                             (logs or {}).items()
                             if isinstance(v, (int, float)))
            print(f"epoch {self._epoch} step {step}: {msg}",
                  file=sys.stderr)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            msg = " - ".join(f"{k}: {v:.4f}" for k, v in
                             (logs or {}).items()
                             if isinstance(v, (int, float)))
            print(f"epoch {epoch} done in {time.time() - self._t0:.1f}s "
                  f"{msg}", file=sys.stderr)


class ModelCheckpoint(Callback):
    """Save every N epochs (reference callbacks.py ModelCheckpoint)."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """(reference callbacks.py EarlyStopping)."""

    def __init__(self, monitor: str = "loss", mode: str = "min",
                 patience: int = 0, min_delta: float = 0.0,
                 baseline=None, save_best_model: bool = False):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.wait = 0
        self.best = baseline
        self.stopped_epoch = 0
        sign = -1 if mode == "max" else 1
        self._sign = sign
        self.stop_training = False

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        score = self._sign * float(cur)
        if self.best is None or score < self._sign * self.best - \
                self.min_delta:
            self.best = float(cur)
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True
                self.stopped_epoch = epoch


class LRSchedulerCallback(Callback):
    """Steps an LRScheduler each epoch/step (reference LRScheduler cb)."""

    def __init__(self, by_step: bool = False, by_epoch: bool = True):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler

        lr = getattr(opt, "_lr", None)
        return lr if isinstance(lr, LRScheduler) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def config_callbacks(callbacks, model, params, verbose=1):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs):
        cbs.insert(0, ProgBarLogger(verbose=verbose))
    return CallbackList(cbs, model, params)
