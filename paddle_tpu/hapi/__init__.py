from .callbacks import (Callback, EarlyStopping, LRSchedulerCallback,
                        ModelCheckpoint, ProgBarLogger)  # noqa: F401
from .model import Model  # noqa: F401

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRSchedulerCallback"]
