"""hapi Model: prepare/fit/evaluate/predict/save/load.

(reference: python/paddle/hapi/model.py — Model.fit:1052, evaluate:1674,
predict:1754; dynamic/static adapters. Here there is one adapter: the
eager path runs op-by-op, and when a fleet hybrid mesh is active the
whole train step is compiled through the ParallelEngine instead — the
TPU-native replacement for the reference's DistributedModel wrapping.)
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..io import DataLoader
from ..metric import Metric
from ..tensor import Tensor, to_tensor
from .callbacks import config_callbacks

__all__ = ["Model"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    """High-level training/eval/predict wrapper around a Layer."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._engine = None
        self._engine_step = None
        self.stop_training = False

    # -- setup ----------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        from ..distributed import fleet

        hcg = fleet.get_hybrid_communicate_group()
        if hcg is not None and optimizer is not None and \
                hcg.mesh.devices.size > 1:
            from ..distributed.engine import ParallelEngine

            self._engine = ParallelEngine(self.network, optimizer,
                                          hcg.mesh)
        return self

    # -- internals ------------------------------------------------------
    def _compute_loss(self, outputs, labels):
        loss = self._loss(outputs, *_as_list(labels)) \
            if not isinstance(self._loss, type(None)) else outputs
        if isinstance(loss, (list, tuple)):
            loss = loss[0]
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        inputs = _as_list(inputs)
        labels = _as_list(labels)
        if self._engine is not None:
            if self._engine_step is None:
                n_in = len(inputs)

                def fn(model, batch):
                    outs = model(*batch["inputs"])
                    return self._compute_loss(outs, batch["labels"])

                self._engine_step = self._engine.train_step(fn)
            batch = {"inputs": [to_tensor(np.asarray(i)) for i in inputs],
                     "labels": [to_tensor(np.asarray(l)) for l in labels]}
            loss = self._engine_step(batch)
            return [float(loss)]
        self.network.train()
        outs = self.network(*[to_tensor(np.asarray(i)) for i in inputs])
        loss = self._compute_loss(outs,
                                  [to_tensor(np.asarray(l))
                                   for l in labels])
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [float(loss)]

    def eval_batch(self, inputs, labels=None):
        from ..autograd import no_grad

        self.network.eval()
        with no_grad():
            outs = self.network(*[to_tensor(np.asarray(i))
                                  for i in _as_list(inputs)])
            lbls = [to_tensor(np.asarray(l)) for l in _as_list(labels)]
            loss = self._compute_loss(outs, lbls) if self._loss else None
            for m in self._metrics:
                if hasattr(m, "compute"):
                    m.update(m.compute(outs, *lbls))
                else:
                    m.update(outs, *lbls)
        return [float(loss)] if loss is not None else []

    def predict_batch(self, inputs):
        from ..autograd import no_grad

        self.network.eval()
        with no_grad():
            outs = self.network(*[to_tensor(np.asarray(i))
                                  for i in _as_list(inputs)])
        return [o.numpy() if isinstance(o, Tensor) else o
                for o in _as_list(outs)]

    @staticmethod
    def _loader(data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    @staticmethod
    def _split_batch(batch):
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if len(batch) >= 2:
            return batch[:-1], batch[-1:]
        return batch, []

    # -- public API -----------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1,
            epochs=1, eval_freq=1, log_freq=10, save_dir=None,
            save_freq=1, verbose=1, drop_last=False, shuffle=True,
            num_workers=0, callbacks=None, **kw):
        loader = self._loader(train_data, batch_size, shuffle)
        cbks = config_callbacks(callbacks, self,
                                {"epochs": epochs, "verbose": verbose},
                                verbose)
        if save_dir:
            from .callbacks import ModelCheckpoint

            cbks.callbacks.append(ModelCheckpoint(save_freq, save_dir))
            cbks.callbacks[-1].set_model(self)
        cbks.call("on_train_begin")
        history = []
        for epoch in range(epochs):
            cbks.call("on_epoch_begin", epoch)
            losses = []
            for step, batch in enumerate(loader):
                cbks.call("on_train_batch_begin", step)
                ins, lbl = self._split_batch(batch)
                loss = self.train_batch(ins, lbl)
                losses.append(loss[0])
                cbks.call("on_train_batch_end", step,
                          {"loss": loss[0]})
            logs = {"loss": float(np.mean(losses)) if losses else 0.0}
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                logs.update(self.evaluate(eval_data,
                                          batch_size=batch_size,
                                          verbose=0))
            cbks.call("on_epoch_end", epoch, logs)
            history.append(logs)
            if any(getattr(c, "stop_training", False)
                   for c in cbks.callbacks):
                self.stop_training = True
                break
        cbks.call("on_train_end")
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 num_workers=0, callbacks=None, **kw):
        loader = self._loader(eval_data, batch_size, shuffle=False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, lbl = self._split_batch(batch)
            out = self.eval_batch(ins, lbl)
            if out:
                losses.append(out[0])
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            acc = m.accumulate()
            name = m.name()
            if isinstance(name, (list, tuple)):
                for n, a in zip(name, _as_list(acc)):
                    logs[f"eval_{n}"] = float(a)
            else:
                logs[f"eval_{name}"] = float(acc)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1, **kw):
        loader = self._loader(test_data, batch_size, shuffle=False)
        outputs = []
        for batch in loader:
            # datasets that also yield labels: feed only the inputs
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence ----------------------------------------------------
    def save(self, path: str, training: bool = True):
        from ..framework import io as _io

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False,
             reset_optimizer: bool = False):
        from ..framework import io as _io

        self.network.set_state_dict(_io.load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_io.load(opt_path))

    def parameters(self, *a, **kw):
        return self.network.parameters(*a, **kw)

    def summary(self, input_size=None, dtype=None):
        lines = [repr(self.network)]
        n = sum(int(np.prod(p.shape)) for p in self.network.parameters())
        lines.append(f"Total params: {n:,}")
        s = "\n".join(lines)
        print(s)
        return {"total_params": n}
