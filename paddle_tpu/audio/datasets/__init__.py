"""Audio datasets (reference: python/paddle/audio/datasets/ — TESS:36,
ESC50:41 download-based loaders).

Zero-egress environment: both parse LOCAL copies of the official
archives (pass the archive/directory path); no downloading. Waveform
decoding covers RIFF/WAV PCM16 natively (numpy); other codecs need an
external decoder and gate loudly.
"""
from __future__ import annotations

import os
import struct
import zipfile
from typing import Callable, Optional

import numpy as np

from ...io import Dataset

__all__ = ["TESS", "ESC50"]


def _read_wav(data: bytes):
    """Minimal RIFF/WAVE PCM16 parser -> (waveform float32 [-1,1], sr)."""
    if data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise NotImplementedError(
            "only RIFF/WAVE PCM files decode natively here")
    pos, sr, bits, n_ch, raw = 12, None, None, 1, None
    while pos + 8 <= len(data):
        cid = data[pos:pos + 4]
        size = struct.unpack("<I", data[pos + 4:pos + 8])[0]
        body = data[pos + 8:pos + 8 + size]
        if cid == b"fmt ":
            fmt, n_ch, sr = struct.unpack("<HHI", body[:8])
            bits = struct.unpack("<H", body[14:16])[0]
            if fmt != 1 or bits != 16:
                raise NotImplementedError(
                    f"WAV fmt={fmt} bits={bits}: only PCM16 decodes "
                    "natively")
        elif cid == b"data":
            raw = body
        pos += 8 + size + (size & 1)
    if sr is None or raw is None:
        raise ValueError("malformed WAV: missing fmt/data chunk")
    wav = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    if n_ch > 1:
        wav = wav.reshape(-1, n_ch).mean(axis=1)
    return wav, sr


class _WavFolderBase(Dataset):
    def __init__(self, path, transform: Optional[Callable] = None):
        from ...core.enforce import enforce

        enforce(path and os.path.exists(path),
                f"{type(self).__name__} needs a LOCAL copy of the "
                "official archive/directory (this environment does not "
                "download); got " + repr(path))
        self.transform = transform
        self._zip = None
        self._files = []
        if os.path.isdir(path):
            for base, _, files in sorted(os.walk(path)):
                for fn in sorted(files):
                    if fn.lower().endswith(".wav"):
                        self._files.append(os.path.join(base, fn))
        else:
            self._zip = zipfile.ZipFile(path)
            self._files = sorted(n for n in self._zip.namelist()
                                 if n.lower().endswith(".wav"))

    def _wav(self, name):
        data = (self._zip.read(name) if self._zip
                else open(name, "rb").read())
        return _read_wav(data)

    def __len__(self):
        return len(self._files)


class TESS(_WavFolderBase):
    """Toronto Emotional Speech Set (reference audio/datasets/tess.py):
    label = the emotion encoded in the file name's last underscore
    field."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad"]

    def __init__(self, path, transform=None):
        super().__init__(path, transform)
        self._files = [f for f in self._files
                       if os.path.splitext(os.path.basename(f))[0]
                       .split("_")[-1].lower() in self.EMOTIONS]

    def __getitem__(self, idx):
        name = self._files[idx]
        stem = os.path.splitext(os.path.basename(name))[0]
        emotion = stem.split("_")[-1].lower()
        label = self.EMOTIONS.index(emotion)
        wav, sr = self._wav(name)
        if self.transform is not None:
            wav = self.transform(wav)
        return wav, np.int64(label)


class ESC50(_WavFolderBase):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py):
    file name format {fold}-{id}-{take}-{target}.wav; split by fold
    (mode='train' keeps folds != split_fold, 'dev' keeps == )."""

    def __init__(self, path, mode: str = "train", split_fold: int = 5,
                 transform: Optional[Callable] = None):
        super().__init__(path, transform)
        keep = []
        for f in self._files:
            stem = os.path.splitext(os.path.basename(f))[0]
            parts = stem.split("-")
            if len(parts) != 4 or not parts[0].isdigit() \
                    or not parts[-1].isdigit():
                continue    # not an ESC-50 clip name; skip
            if (int(parts[0]) != split_fold) == (mode == "train"):
                keep.append(f)
        self._files = keep

    def __getitem__(self, idx):
        name = self._files[idx]
        stem = os.path.splitext(os.path.basename(name))[0]
        label = int(stem.split("-")[-1])
        wav, sr = self._wav(name)
        if self.transform is not None:
            wav = self.transform(wav)
        return wav, np.int64(label)
