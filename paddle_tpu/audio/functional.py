"""Audio functional ops (reference: python/paddle/audio/functional/
functional.py + window.py). Pure jnp formulas — every window/filterbank
is built on device and constant-folded into the surrounding XLA program.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..core.dispatch import def_op
from ..core.enforce import enforce

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def _hz_to_mel_val(freq, htk):
    if htk:
        return 2595.0 * jnp.log10(1.0 + jnp.asarray(freq) / 700.0)
    f = jnp.asarray(freq, jnp.float32)
    f_sp = 200.0 / 3
    mels = f / f_sp
    min_log_hz = 1000.0
    logstep = math.log(6.4) / 27.0
    return jnp.where(f >= min_log_hz,
                     min_log_hz / f_sp + jnp.log(
                         jnp.maximum(f, min_log_hz) / min_log_hz) / logstep,
                     mels)


def _mel_to_hz_val(mel, htk):
    if htk:
        return 700.0 * (10.0 ** (jnp.asarray(mel) / 2595.0) - 1.0)
    m = jnp.asarray(mel, jnp.float32)
    f_sp = 200.0 / 3
    min_log_mel = 1000.0 / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(m >= min_log_mel,
                     1000.0 * jnp.exp(logstep * (
                         jnp.maximum(m, min_log_mel) - min_log_mel)),
                     f_sp * m)


@def_op("hz_to_mel", differentiable=False)
def hz_to_mel(freq, htk=False):
    return _hz_to_mel_val(freq, bool(htk))


@def_op("mel_to_hz", differentiable=False)
def mel_to_hz(mel, htk=False):
    return _mel_to_hz_val(mel, bool(htk))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    lo = _hz_to_mel_val(f_min, htk)
    hi = _hz_to_mel_val(f_max, htk)
    mels = jnp.linspace(lo, hi, int(n_mels))
    from ..tensor import to_tensor

    return to_tensor(_mel_to_hz_val(mels, htk).astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    from ..tensor import to_tensor

    return to_tensor(jnp.linspace(
        0.0, float(sr) / 2, 1 + int(n_fft) // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank."""
    if f_max is None:
        f_max = float(sr) / 2
    fft_f = jnp.linspace(0.0, float(sr) / 2, 1 + int(n_fft) // 2)
    lo = _hz_to_mel_val(f_min, htk)
    hi = _hz_to_mel_val(f_max, htk)
    mel_f = _mel_to_hz_val(jnp.linspace(lo, hi, int(n_mels) + 2), htk)

    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]          # [n_mels+2, F]
    lower = -ramps[:-2] / fdiff[:-1][:, None]
    upper = ramps[2:] / fdiff[1:][:, None]
    fb = jnp.maximum(0.0, jnp.minimum(lower, upper))  # [n_mels, F]
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:] - mel_f[:-2])
        fb = fb * enorm[:, None]
    from ..tensor import to_tensor

    return to_tensor(fb.astype(dtype))


@def_op("power_to_db")
def power_to_db(x, ref_value=1.0, amin=1e-10, top_db=80.0):
    enforce(float(amin) > 0, lambda: "amin must be strictly positive")
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(float(amin),
                                                float(ref_value)))
    if top_db is not None:
        enforce(float(top_db) >= 0, lambda: "top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - float(top_db))
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II transform matrix."""
    n = jnp.arange(int(n_mels), dtype=jnp.float32)
    k = jnp.arange(int(n_mfcc), dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        scale = jnp.full((int(n_mfcc),), math.sqrt(2.0 / n_mels))
        scale = scale.at[0].set(math.sqrt(1.0 / n_mels))
        dct = dct * scale[None, :]
    else:
        dct = dct * 2.0
    from ..tensor import to_tensor

    return to_tensor(dct.astype(dtype))


def _window_values(name, M, fftbins, **kwargs):
    """Periodic (fftbins=True) or symmetric window of length M."""
    sym_len = M + 1 if fftbins else M
    n = jnp.arange(sym_len, dtype=jnp.float32)
    if sym_len == 1:
        w = jnp.ones((1,))
    elif name == "hann":
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * n / (sym_len - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * n / (sym_len - 1))
    elif name == "blackman":
        x = 2 * math.pi * n / (sym_len - 1)
        w = 0.42 - 0.5 * jnp.cos(x) + 0.08 * jnp.cos(2 * x)
    elif name in ("bartlett", "triang"):
        if name == "bartlett":
            w = 1.0 - jnp.abs(2 * n / (sym_len - 1) - 1.0)
        else:
            m = (sym_len + 1) // 2
            ramp = (jnp.arange(1, m + 1) - 0.5 * ((sym_len + 1) % 2)) \
                / ((sym_len + (sym_len % 2)) / 2.0)
            ramp = jnp.minimum(ramp, 1.0)
            w = jnp.concatenate(
                [ramp, ramp[::-1][(1 if sym_len % 2 else 0):]])[:sym_len]
    elif name == "cosine":
        w = jnp.sin(math.pi / sym_len * (n + 0.5))
    elif name == "bohman":
        x = jnp.abs(2 * n / (sym_len - 1) - 1.0)
        w = (1 - x) * jnp.cos(math.pi * x) + jnp.sin(math.pi * x) / math.pi
    elif name == "gaussian":
        std = kwargs.get("std", 7.0)
        center = (sym_len - 1) / 2.0
        w = jnp.exp(-0.5 * ((n - center) / std) ** 2)
    elif name == "exponential":
        tau = kwargs.get("tau", 1.0)
        center = (sym_len - 1) / 2.0
        w = jnp.exp(-jnp.abs(n - center) / tau)
    elif name == "tukey":
        alpha = kwargs.get("alpha", 0.5)
        if alpha <= 0:
            w = jnp.ones((sym_len,))
        elif alpha >= 1:
            w = 0.5 - 0.5 * jnp.cos(2 * math.pi * n / (sym_len - 1))
        else:
            edge = alpha * (sym_len - 1) / 2.0
            left = 0.5 * (1 + jnp.cos(math.pi * (n / edge - 1)))
            right = 0.5 * (1 + jnp.cos(
                math.pi * ((n - (sym_len - 1)) / edge + 1)))
            w = jnp.where(n < edge, left,
                          jnp.where(n > sym_len - 1 - edge, right, 1.0))
    else:
        raise ValueError(f"unsupported window {name!r}")
    return w[:M] if fftbins else w


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window by name or (name, param) tuple (reference: audio/
    functional/window.py get_window)."""
    kwargs = {}
    if isinstance(window, tuple):
        name = window[0]
        if len(window) > 1:
            key = {"gaussian": "std", "exponential": "tau",
                   "tukey": "alpha"}.get(name, "param")
            kwargs[key] = window[1]
    else:
        name = window
    w = _window_values(name, int(win_length), bool(fftbins), **kwargs)
    from ..tensor import to_tensor

    return to_tensor(w.astype(dtype))
