"""Audio feature layers (reference: python/paddle/audio/features/
layers.py — Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC). Each
forward is stft -> |.|^p -> fbank/DCT matmuls, all inside one XLA
program when jitted.
"""
from __future__ import annotations

from .. import nn, signal
from ..ops.math import matmul
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(nn.Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.window, center=self.center,
                           pad_mode=self.pad_mode)
        if self.power == 2.0:  # power spectrum: skip the |.| sqrt
            from ..ops.math import imag, real

            re, im = real(spec), imag(spec)
            return re * re + im * im
        mag = spec.abs()
        return mag if self.power == 1.0 else mag.pow(self.power)


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm, dtype)

    def forward(self, x):
        spec = self.spectrogram(x)          # [..., F, T]
        return matmul(self.fbank, spec)     # dispatched: autograd flows


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.mel_spectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self.mel_spectrogram(x), self.ref_value,
                           self.amin, self.top_db)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct = create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        mel = self.log_mel(x)               # [..., n_mels, T]
        # dct.T [n_mfcc, n_mels] @ mel -> [..., n_mfcc, T] (dispatched:
        # autograd flows)
        return matmul(self.dct, mel, transpose_x=True)
