"""paddle.audio analog (reference: python/paddle/audio/ — functional
windows + mel utilities and feature layers built on paddle.signal.stft;
backends/datasets are file-IO helpers outside the compute scope).
"""
from . import datasets, features, functional  # noqa: F401

__all__ = ["functional", "features", "datasets"]
