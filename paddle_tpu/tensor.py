"""The eager Tensor type.

TPU-native analog of the reference's ``paddle.Tensor``
(reference: paddle/phi/api/include/tensor.h:82 paddle::Tensor value type;
autograd metadata paddle/fluid/eager/autograd_meta.h:61; python methods
monkey-patched by paddle/fluid/pybind/eager_method.cc).

A Tensor wraps an immutable ``jax.Array`` (or a Tracer under jit) plus
autograd metadata (``stop_gradient``, ``grad``, producer GradNode). All
math/manipulation methods are monkey-patched in ``tensor_methods.py`` —
the same late-binding strategy the reference uses for its pybind Tensor.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
import jax.numpy as jnp

from .core.dtype import convert_dtype, get_default_dtype

__all__ = ["Tensor", "Parameter", "to_tensor", "inplace_swap"]


def inplace_swap(target: "Tensor", out: "Tensor") -> "Tensor":
    """The single definition of the ``foo_`` in-place contract: swap the
    functional result into ``target`` (value + autograd producer +
    output slot; stop_gradient only loosens). Used by tensor_methods,
    nn.functional inplace variants, and the top-level foo_ family."""
    target._value = out._value
    target._grad_node = out._grad_node
    target._out_idx = out._out_idx
    if not out.stop_gradient:
        target.stop_gradient = False
    return target


class Tensor:
    __slots__ = (
        "_value", "stop_gradient", "grad", "_grad_node", "_out_idx",
        "name", "persistable", "trainable", "_grad_hooks", "dist_attr",
        "__weakref__", "__dict__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad: Optional["Tensor"] = None
        self._grad_node = None
        self._out_idx = 0
        self.name = name or ""
        self.persistable = False
        self.trainable = not stop_gradient
        self._grad_hooks = None
        self.dist_attr = None

    # -- structural properties ------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return jnp.dtype(self._value.dtype)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def place(self):
        try:
            return next(iter(self._value.devices()))
        except Exception:
            return "traced"

    # -- interop --------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __jax_array__(self):
        return self._value

    def item(self):
        return self._value.item()

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __hash__(self):
        return id(self)

    # -- autograd -------------------------------------------------------
    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False) -> None:
        from .autograd import engine
        engine.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self) -> None:
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .ops import manipulation
        return manipulation.assign(self)

    def register_hook(self, hook):
        """Register a grad hook fired when this leaf's grad accumulates."""
        if self._grad_hooks is None:
            self._grad_hooks = []
        self._grad_hooks.append(hook)

        class _Removable:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Removable(self._grad_hooks, hook)

    # -- value mutation (functional under the hood) ---------------------
    def copy_(self, other: "Tensor") -> "Tensor":
        self._value = other._value if isinstance(other, Tensor) else jnp.asarray(other)
        return self

    def set_value(self, value) -> None:
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value, dtype=self._value.dtype)

    def fill_(self, v) -> "Tensor":
        self._value = jnp.full_like(self._value, v)
        return self

    def zero_(self) -> "Tensor":
        self._value = jnp.zeros_like(self._value)
        return self

    def _replace_value(self, value) -> None:
        """Internal: swap the backing array (optimizer updates)."""
        self._value = value

    def __setitem__(self, idx, value) -> None:
        if isinstance(value, Tensor):
            value = value._value
        if isinstance(idx, Tensor):
            idx = idx._value
        if isinstance(idx, tuple):
            idx = tuple(i._value if isinstance(i, Tensor) else i for i in idx)
        self._value = self._value.at[idx].set(value)

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}{grad_info},\n"
                f"       {self._value})")


class Parameter(Tensor):
    """Trainable tensor (analog of paddle's EagerParamBase)."""

    def __init__(self, value, name: Optional[str] = None, trainable: bool = True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """Create a Tensor from python/numpy data (paddle.to_tensor)."""
    if isinstance(data, Tensor):
        value = data._value
        if dtype is not None:
            value = value.astype(convert_dtype(dtype))
        return Tensor(value, stop_gradient=stop_gradient)
    if dtype is None:
        if isinstance(data, (bool, np.bool_)):
            pass  # bool stays bool
        elif isinstance(data, (float,)):
            dtype = get_default_dtype()
        elif isinstance(data, np.ndarray) and data.dtype == np.float64:
            dtype = get_default_dtype()
    value = jnp.asarray(data, dtype=convert_dtype(dtype) if dtype is not None else None)
    return Tensor(value, stop_gradient=stop_gradient)
