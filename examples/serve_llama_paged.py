"""Serve an LLM with the paged KV cache and ragged batching.

One compiled prefill + the WHOLE decode loop as one XLA program;
mixed-length prompts decode at per-row offsets, stop per row at EOS,
and the KV cache is a paged pool (pages allocated per row, block-table
indirection inside the Pallas kernel on TPU).

    python examples/serve_llama_paged.py          # tiny model, CPU ok
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny())   # swap in llama_7b() on TPU

    conf = (Config().set_model(model)
            .enable_paged_kv(page_size=16))
    # conf.enable_weight_only("weight_only_int8")   # int8 weights in HBM
    pred = create_predictor(conf)

    # three prompts of different lengths, right-padded
    r = np.random.RandomState(0)
    lens = [11, 24, 17]
    ids = np.zeros((3, max(lens)), np.int64)
    for b, L in enumerate(lens):
        ids[b, :L] = r.randint(1, model.config.vocab_size, (L,))

    out = pred.generate(paddle.to_tensor(ids), max_new_tokens=8,
                        lengths=lens, temperature=0.0)
    for b, L in enumerate(lens):
        print(f"prompt[{b}] len={L:2d} -> new tokens:",
              out.numpy()[b, max(lens):].tolist())


if __name__ == "__main__":
    main()
