"""Serve an LLM with the paged KV cache, ragged batching, and the
continuous-batching ServingEngine.

Static batch: one compiled prefill + the WHOLE decode loop as one XLA
program; mixed-length prompts decode at per-row offsets, stop per row
at EOS, and the KV cache is a paged pool (pages allocated per row,
block-table indirection inside the Pallas kernel on TPU).

Traffic: ServingEngine admits a request STREAM into an in-flight
batch — per-arrival bucketed prefill, one shared decode step, early
rows evicted (pages back on the free list) and backfilled from the
queue, all on a fixed program lattice (zero recompiles after warmup).

    python examples/serve_llama_paged.py          # tiny model, CPU ok
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import Config, ServingEngine, create_predictor
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny())   # swap in llama_7b() on TPU

    conf = (Config().set_model(model)
            .enable_paged_kv(page_size=16))
    # conf.enable_weight_only("weight_only_int8")   # int8 weights in HBM
    pred = create_predictor(conf)

    # --- static ragged batch: one generate() call -----------------------
    r = np.random.RandomState(0)
    lens = [11, 24, 17]
    ids = np.zeros((3, max(lens)), np.int64)
    for b, L in enumerate(lens):
        ids[b, :L] = r.randint(1, model.config.vocab_size, (L,))

    out = pred.generate(paddle.to_tensor(ids), max_new_tokens=8,
                        lengths=lens, temperature=0.0)
    for b, L in enumerate(lens):
        print(f"prompt[{b}] len={L:2d} -> new tokens:",
              out.numpy()[b, max(lens):].tolist())

    # --- continuous batching: a request stream --------------------------
    eng = ServingEngine(pred, max_batch=2, decode_chunk=4)
    rids = [eng.submit(r.randint(1, model.config.vocab_size, (L,)),
                       max_new_tokens=6)
            for L in (7, 19, 4, 13, 9)]      # more requests than slots
    done = eng.run()                          # evict + backfill inside
    for rid in rids:
        req = done[rid]
        print(f"request {rid} len={len(req.prompt):2d} -> ",
              req.new_tokens)
    print("compile telemetry:", eng.stats.as_dict())

    # --- chunked prefill: long prompts no longer stall decode rows ------
    # prompts feed the unified ragged [B, Sc] step in page-aligned
    # chunks; decode rows advance EVERY round (same outputs, flatter
    # TPOT tail under mixed traffic)
    eng = ServingEngine(pred, max_batch=2, prefill_chunk=32)
    rids = [eng.submit(r.randint(1, model.config.vocab_size, (L,)),
                       max_new_tokens=6)
            for L in (64, 9, 5)]             # one long, two short
    done = eng.run()
    for rid in rids:
        req = done[rid]
        print(f"chunked request {rid} len={len(req.prompt):2d} -> ",
              req.new_tokens)
    print("compile telemetry:", eng.stats.as_dict())


if __name__ == "__main__":
    main()
