"""Image classification with the vision zoo + the hapi high-level API.

    python examples/vision_classify.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import hapi, nn
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.models import resnet18


class RandomImages:
    """Stand-in dataset: 2-class random images (swap in
    paddle.vision.datasets + transforms for real data)."""

    def __init__(self, n=64):
        r = np.random.RandomState(0)
        self.x = r.rand(n, 3, 32, 32).astype("float32")
        self.y = (self.x.mean(axis=(1, 2, 3)) > 0.5).astype("int64")

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def main():
    paddle.seed(0)
    model = hapi.Model(resnet18(num_classes=2))
    model.prepare(
        optimizer=paddle.optimizer.Momentum(
            learning_rate=0.005, momentum=0.9,
            parameters=model.network.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    model.fit(RandomImages(), epochs=3, batch_size=16, verbose=1)
    print(model.evaluate(RandomImages(32), batch_size=16, verbose=1))


if __name__ == "__main__":
    main()
