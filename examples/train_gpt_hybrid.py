"""Train a GPT with hybrid parallelism (dp x mp) on a device mesh.

Runs on the 8-virtual-device CPU mesh out of the box; on a TPU pod the
same code uses the real chips (the mesh axes become ICI):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/train_gpt_hybrid.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)


def main():
    import jax

    if jax.device_count() < 8:
        print("need 8 devices; run with the env shown in the docstring")
        return
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                    num_heads=4, max_position_embeddings=128)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())

    # ONE compiled XLA program per step: forward + backward + AdamW,
    # with tensor-parallel collectives riding the mesh
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))

    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (8, 65))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    for i in range(10):
        loss = step(batch)
        print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
