"""Perf experiments queued for the next on-chip session (the axon TPU
tunnel was down for most of round 4 session 2 — see PARITY.md).

Run when a chip is attached:

    python bench_experiments.py          # all experiments
    python bench_experiments.py b8       # one by name

Baseline to beat (measured this round before the outage):
GPT-1.3B train 0.5398 MFU / 13,491 tok/s at B=4; llama-7B decode
46.8 tok/s @ ctx 2048 (77% of the bf16 HBM roofline).
"""
import json
import subprocess
import sys
import time


def probe_chip(timeout_s: int = 45) -> bool:
    # single probe implementation lives in bench.py (_probe_chip adds
    # the retry + CPU-fallback reporting policy on top)
    from bench import _probe_chip

    return _probe_chip()


def exp_b8():
    """GPT-1.3B at B=8 (vs the B=4 baseline): more MXU work per step.
    Watch for HBM pressure — if it OOMs, B=6 is the fallback."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    for B in (8, 6):
        try:
            cfg = GPTConfig(vocab_size=50304, hidden_size=2048,
                            num_layers=24, num_heads=16,
                            max_position_embeddings=1024,
                            dtype="bfloat16")
            paddle.seed(0)
            model = GPTForCausalLM(cfg)
            crit = GPTPretrainingCriterion(cfg)
            opt = paddle.optimizer.AdamW(
                learning_rate=1e-4, parameters=model.parameters(),
                state_dtype="bfloat16")
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
            hcg = fleet.init(is_collective=True, strategy=strategy)
            eng = ParallelEngine(model, opt, hcg.mesh)
            step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
            r = np.random.RandomState(0)
            ids = r.randint(0, cfg.vocab_size, (B, 1025))
            batch = {"x": paddle.to_tensor(ids[:, :-1]),
                     "y": paddle.to_tensor(ids[:, 1:])}
            float(step(batch))
            t0 = time.perf_counter()
            for _ in range(5):
                loss = step(batch)
            float(loss)
            dt = time.perf_counter() - t0
            tok_s = B * 1024 * 5 / dt
            mfu = 6.0 * cfg.num_params() * tok_s / 197e12
            print(json.dumps({"experiment": f"gpt1p3b_B{B}",
                              "tokens_per_sec": round(tok_s, 1),
                              "mfu": round(mfu, 4),
                              "baseline_mfu": 0.5398}))
            return
        except Exception as e:  # noqa: BLE001 (try the smaller B)
            print(json.dumps({"experiment": f"gpt1p3b_B{B}",
                              "error": f"{type(e).__name__}: {e}"[:200]}))


def exp_autotune():
    """Flash-attention block autotuning on chip (FLAGS_use_autotune):
    measured block search vs the static pick_block heuristics."""
    import paddle_tpu as paddle

    paddle.set_flags({"FLAGS_use_autotune": True})
    subprocess.run([sys.executable, "bench.py", "--only", "gpt"])


def exp_int8_decode():
    """Weight-only int8 llama decode (new bench line): expect to beat
    46.8 tok/s since most weight bytes halve."""
    subprocess.run([sys.executable, "bench.py", "--only",
                    "llama_decode_int8"])


def main(argv):
    exps = {"b8": exp_b8, "autotune": exp_autotune,
            "int8_decode": exp_int8_decode}
    if not probe_chip():
        print(json.dumps({"error": "no TPU chip reachable"}))
        return
    names = argv[1:] or list(exps)
    for n in names:
        exps[n]()


if __name__ == "__main__":
    main(sys.argv)
